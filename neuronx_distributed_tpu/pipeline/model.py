"""Pipeline-parallel runtime (reference: ``pipeline/model.py`` ``NxDPPModel:80``).

The reference FX-traces the model, partitions the graph, and hand-executes
scheduler task lists per process with send/recv-as-allgather and a mark_step
per task (model.py:1737, comm.py:40). The TPU-native runtime instead compiles
the ENTIRE schedule into one XLA program:

  * ``shard_map`` over ONLY the ``pp`` mesh axis — tp/dp/cp stay "auto", so the
    GSPMD layers (ColumnParallel/RowParallel/...) keep working inside each
    stage and XLA still inserts/overlaps their collectives;
  * stage weights are the scan-stacked layer params reshaped (L,...) →
    (S, L/S, ...) with the stage dim sharded over pp — each rank holds its
    stage's layers;
  * the microbatch loop is a ``lax.scan`` of M + S - 1 ticks; each tick every
    stage applies its layers and passes activations to the next stage with a
    non-wrapping ``lax.ppermute`` (the TPU-native replacement for the
    reference's 2-rank-allgather p2p, pipeline/comm.py:40);
  * backward comes from ``jax.grad`` through the scan: XLA reverses the
    ppermutes, giving the mirrored drain schedule. Per-layer ``jax.checkpoint``
    bounds activation memory (the role 1F1B plays in the reference; here the
    schedule is GPipe-shaped with rematerialized stages — same bubble fraction,
    bounded memory). The pure-Python 1F1B/interleaved task streams live in
    pipeline/scheduler.py as the semantic contract and for an explicitly
    scheduled runtime.

Shared-weight (tied embedding) grad sync (reference model.py:1687) is automatic:
embedding params enter the loss once via stage 0's compute, and autodiff sums
contributions — there is no second copy to reconcile.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class PipelineEngine:
    """Compiles a (embed → scanned layers → head) model into a pp-pipelined
    loss function.

    ``embed_apply(embed_params, mb_batch) -> x``
    ``layer_apply(layer_params, x) -> x``            (one layer; scanned)
    ``head_apply(head_params, x, mb_batch) -> (loss_sum, weight_sum)``
    """

    embed_apply: Callable
    layer_apply: Callable
    head_apply: Callable
    num_layers: int
    num_microbatches: int
    remat_layers: bool = True
    # Per-microbatch loss weight (must equal head_apply's weight_sum for the
    # same microbatch — used by OneFOneBEngine to seed head cotangents before
    # any head has run). Default: loss_mask.sum(), else the label count.
    weight_fn: Optional[Callable] = None
    # MoE-style per-layer auxiliary losses: when True, ``layer_apply`` returns
    # ``(x, aux_scalar)`` (pre-weighted by the adapter's coefficients) and the
    # engines add ``mean-over-microbatches`` of the summed aux to the loss —
    # the per-microbatch formulation the reference's MoE aux wiring implies
    # (modules/moe/loss_function.py via returned router logits).
    layer_aux: bool = False

    def _microbatch_weight(self, mb_batch):
        if self.weight_fn is not None:
            return self.weight_fn(mb_batch)
        mask = mb_batch.get("loss_mask")
        if mask is not None:
            return mask.sum().astype(jnp.float32)
        return jnp.asarray(float(mb_batch["labels"].size), jnp.float32)

    def _stages(self) -> int:
        return mesh_lib.get_pipeline_model_parallel_size()

    # --- param layout ---------------------------------------------------------

    def stack_layer_specs(self, layer_specs):
        """(L, ...) per-layer specs → (S, L/S, ...) with pp on the stage dim."""

        def fix(spec):
            entries = list(spec)
            # leading dim is the stacked layer dim: becomes (pp, layers/stage)
            rest = entries[1:] if entries else []
            return P(mesh_lib.PP_AXIS, None, *rest)

        return jax.tree.map(fix, layer_specs, is_leaf=lambda s: isinstance(s, P))

    def reshape_layer_params(self, layer_params):
        """Physically reshape stacked layer leaves (L, ...) → (S, L/S, ...)."""
        S = self._stages()
        L = self.num_layers
        if L % S != 0:
            raise ValueError(f"num_layers {L} not divisible by {S} pipeline stages")

        def reshape(a):
            return a.reshape((S, L // S) + a.shape[1:])

        return jax.tree.map(reshape, layer_params)

    def unshape_layer_params(self, layer_params):
        def reshape(a):
            return a.reshape((self.num_layers,) + a.shape[2:])

        return jax.tree.map(reshape, layer_params)

    # --- the pipelined loss ---------------------------------------------------

    def loss_fn(self, params, batch):
        """params = {"embed":…, "layers": (S, L/S, …) leaves, "head":…};
        batch leaves shaped (M, mb, ...). Returns mean loss.

        Only embed + layers run inside the pp-manual region; the head runs
        OUTSIDE in plain GSPMD on the last stage's collected outputs. (Besides
        being cleaner, this sidesteps an XLA SPMD-partitioner CHECK crash —
        spmd_partitioner_util.cc:495, jaxlib 0.9 — triggered by lax.cond
        branches touching sharded operands inside a partial-manual shard_map.)
        XLA slices the collected-output tensor at the last stage, so only that
        stage's activations move."""
        final, aux_stacked = self._run_pipeline(params, batch, remat=True)
        lsum, wsum = self.head_apply(params["head"], final, batch)
        loss = lsum / jnp.maximum(wsum, 1.0)
        if self.layer_aux:
            loss = loss + aux_stacked.sum() / self.num_microbatches
        return loss

    def _run_pipeline(self, params, batch, remat: bool):
        """The skewed tick loop shared by :meth:`loss_fn` (differentiated)
        and :meth:`forward` (inference): embed once, M+S-1 ticks of
        stage-apply + non-wrapping ppermute, returning the last stage's
        per-microbatch outputs and the per-rank aux totals (stacked over pp)."""
        mesh = mesh_lib.get_mesh()
        S = self._stages()
        M = self.num_microbatches
        layer_apply = (
            jax.checkpoint(self.layer_apply) if remat and self.remat_layers
            else self.layer_apply
        )
        stage_fn = self._make_stage_fn(layer_apply)

        def pipelined(layers_local, embed_params, batch):
            rank = mesh_lib.compat_axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[0], layers_local)  # drop stage dim
            # Embed all M microbatches once, OUTSIDE the tick loop: the loop
            # otherwise pays M+S-1 embedding fwd (and bwd) passes per stage for
            # the M that are used, and the differentiated scan grows with it.
            embedded = jax.vmap(lambda mb: self.embed_apply(embed_params, mb))(batch)
            buf = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def tick(carry, t):
                buf, aux_acc = carry
                mb_in = jnp.clip(t, 0, M - 1)
                x_in = lax.dynamic_index_in_dim(embedded, mb_in, 0, keepdims=False)
                x = jnp.where(rank == 0, x_in, buf)
                y, aux = stage_fn(layers_local, x)
                # aux only counts for ticks carrying a REAL microbatch on
                # this rank (tick t processes mb = t - rank)
                mb = t - rank
                valid = ((mb >= 0) & (mb < M)).astype(aux.dtype)
                aux_acc = aux_acc + aux * valid
                if S > 1:
                    buf_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, i + 1) for i in range(S - 1)]
                    )
                else:
                    buf_next = y
                return (buf_next, aux_acc), y

            (_, aux_acc), ys = lax.scan(
                tick, (buf, jnp.zeros((), jnp.float32)), jnp.arange(M + S - 1)
            )
            # this rank's stage outputs per tick + its layers' aux total
            return ys, aux_acc[None]

        fn = mesh_lib.compat_shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(mesh_lib.PP_AXIS), P(), P()),
            out_specs=(P(mesh_lib.PP_AXIS), P(mesh_lib.PP_AXIS)),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        ys, aux_stacked = fn(params["layers"], params["embed"], batch)
        # (S·(M+S-1), mb, ...) → last stage's valid window = microbatch outputs
        ticks = M + S - 1
        ys = ys.reshape((S, ticks) + ys.shape[1:])
        final = ys[S - 1, S - 1 :]  # (M, mb, ...)
        return final, aux_stacked

    def forward(self, params, batch, head_fn: Optional[Callable] = None):
        """Forward-only pipelined inference — the ``InferenceSchedule``
        (recv → fwd → send per microbatch, reference scheduler.py:144)
        realized as the same skewed tick loop without a backward. Returns
        the last stage's outputs per microbatch ``(M, mb, ...)``; with
        ``head_fn(head_params, x)`` the head is applied to them (e.g. final
        norm + lm_head for PP logits)."""
        final, _aux = self._run_pipeline(params, batch, remat=False)
        if head_fn is not None:
            final = head_fn(params["head"], final)
        return final

    def _make_stage_fn(self, layer_apply):
        """Scan the local layers; with ``layer_aux`` the carry also sums the
        per-layer (pre-weighted) aux scalars."""
        if self.layer_aux:

            def stage_fn(lp, x):
                def body(carry, one_layer):
                    h, acc = carry
                    h, aux = layer_apply(one_layer, h)
                    return (h, acc + aux.astype(jnp.float32)), None

                (out, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), lp)
                return out, aux

            return stage_fn

        def stage_fn(lp, x):
            def body(h, one_layer):
                return layer_apply(one_layer, h), None

            out, _ = lax.scan(body, x, lp)
            return out, jnp.zeros((), jnp.float32)

        return stage_fn


@dataclasses.dataclass
class OneFOneBEngine(PipelineEngine):
    """Explicitly-scheduled synchronous 1F1B runtime, with interleaved
    (virtual-pipeline) chunks at ``num_chunks > 1`` (VERDICT.md missing #2/#6;
    reference ``pipeline/model.py:1737`` ``_exec_schedule`` over
    ``Train1F1BSchedule`` / ``TrainInterleavedSchedule``, virtual chunks via
    ``get_current_stage`` model.py:1053).

    Unlike :class:`PipelineEngine` (scan-GPipe: one forward scan, backward by
    ``jax.grad`` reversing it, activation memory O(M) stage-inputs under
    remat), this engine *is* the scheduler: grads are computed inside the
    cycle loop, never by differentiating it. With S stages, C chunks and the
    mixed-radix decomposition ``u = g·S·C + k·S + i`` each cycle rank r

      * forwards ``u = c - r``: microbatch ``g·S + i`` through its chunk-k
        layers (recv → stage → send via a full-rotation ``ppermute`` — rank
        S-1's chunk-k output wraps to rank 0's chunk-k+1 input), storing only
        the stage INPUT in a depth-``min(2SC-1, MC)`` circular buffer,
      * backwards ``u' = c - (SC-1) - (S-1-r)`` mirrored (chunk ``C-1-k'``):
        pop the saved input, ``jax.vjp`` recomputes the stage forward and
        pulls the cotangent back, accumulate param grads into the chunk-k
        slot, send the input-cotangent down-rotation,

    which is ``SyncTrainInterleavedSchedule`` (≡ ``SyncTrain1F1BSchedule`` at
    C=1) — see its docstring for the bubble accounting: interleaving shrinks
    the sync-lockstep bubble from ``2(S-1)`` toward ``S`` stage-units.
    Activation memory is O(S·C) stage-inputs, independent of M. Compute per
    microbatch is identical to GPipe (both pay the remat 4/3).

    The loss head (last rank, chunk C-1) is gated behind a rank-dependent
    ``lax.cond`` so other ranks skip its vocab-sized matmul+CE at runtime;
    the embedding fwd/bwd runs outside in plain GSPMD, connected through an
    explicit (M, ...) cotangent buffer.
    """

    num_chunks: int = 1

    def _cycle_tables(self):
        """Per-rank (fwd mb/chunk, bwd mb/chunk) per cycle, derived from the
        task stream of SyncTrainInterleavedSchedule — the scheduler is the
        source of truth; the closed forms inside the scan body are asserted
        against it here."""
        from neuronx_distributed_tpu.pipeline.scheduler import (
            BackwardTask,
            ForwardTask,
            SyncTrainInterleavedSchedule,
            validate_schedule,
        )

        S, M, C = self._stages(), self.num_microbatches, self.num_chunks
        cycles = M * C + S * C + S - 2
        for r in range(S):
            sched = SyncTrainInterleavedSchedule(M, S, r, num_chunks=C)
            validate_schedule(sched)
            assert cycles == sched.num_cycles
            fwd = [(t.mb, t.chunk) for t in sched.steps() if isinstance(t, ForwardTask)]
            bwd = [(t.mb, t.chunk) for t in sched.steps() if isinstance(t, BackwardTask)]
            want_fwd, want_bwd = [], []
            for c in range(cycles):
                u = c - r
                if 0 <= u < M * C:
                    g, rem = divmod(u, S * C)
                    k, i = divmod(rem, S)
                    want_fwd.append((g * S + i, k))
                ub = c - (S * C - 1) - (S - 1 - r)
                if 0 <= ub < M * C:
                    g, rem = divmod(ub, S * C)
                    kp, i = divmod(rem, S)
                    want_bwd.append((g * S + i, C - 1 - kp))
            if fwd != want_fwd or bwd != want_bwd:
                raise AssertionError(
                    f"cycle tables diverge from SyncTrainInterleavedSchedule at rank {r}"
                )
        return cycles

    def _fwd_slot(self, c, rank):
        """Mixed-radix forward-slot decode ``u = c - rank = g·SC + k·S + i``
        shared by :meth:`value_and_grad` and
        :meth:`_run_interleaved_forward` (ONE copy of the schedule math —
        validated against ``SyncTrainInterleavedSchedule`` by
        :meth:`_cycle_tables`). Returns ``(fwd_valid, k_f, mb_f, u_c)`` where
        ``u_c`` is the clamped slot id (the circular activation buffer keys
        off it)."""
        S, C = self._stages(), self.num_chunks
        MC = self.num_microbatches * C
        SC = S * C
        u = c - rank
        fwd_valid = (u >= 0) & (u < MC)
        u_c = jnp.clip(u, 0, MC - 1)
        k_f = (u_c % SC) // S
        mb_f = (u_c // SC) * S + (u_c % S)
        return fwd_valid, k_f, mb_f, u_c

    # --- interleaved param layout: (L,...) → (C, S, L/(S·C), ...) -------------
    # Virtual stage v = k·S + r covers layers [v·Lc, (v+1)·Lc), so a plain
    # reshape to (C, S, Lc) puts chunk k of rank r at [k, r] exactly.

    def stack_layer_specs(self, layer_specs):
        if self.num_chunks == 1:
            return super().stack_layer_specs(layer_specs)

        def fix(spec):
            entries = list(spec)
            rest = entries[1:] if entries else []
            return P(None, mesh_lib.PP_AXIS, None, *rest)

        return jax.tree.map(fix, layer_specs, is_leaf=lambda s: isinstance(s, P))

    def reshape_layer_params(self, layer_params):
        if self.num_chunks == 1:
            return super().reshape_layer_params(layer_params)
        S, C, L = self._stages(), self.num_chunks, self.num_layers
        if L % (S * C) != 0:
            raise ValueError(
                f"num_layers {L} not divisible by stages×chunks {S}×{C}"
            )
        return jax.tree.map(
            lambda a: a.reshape((C, S, L // (S * C)) + a.shape[1:]), layer_params
        )

    def unshape_layer_params(self, layer_params):
        if self.num_chunks == 1:
            return super().unshape_layer_params(layer_params)
        return jax.tree.map(
            lambda a: a.reshape((self.num_layers,) + a.shape[3:]), layer_params
        )

    def value_and_grad(self, params, batch):
        """(loss, grads) with grads computed by the explicit sync-1F1B /
        interleaved schedule. Same params/batch layout as
        :meth:`PipelineEngine.loss_fn` (layers gain a leading chunk dim when
        ``num_chunks > 1``)."""
        mesh = mesh_lib.get_mesh()
        S = self._stages()
        M = self.num_microbatches
        C = self.num_chunks
        if C > 1 and M % S != 0:
            raise ValueError(
                f"interleaved pipeline needs microbatches divisible by stages "
                f"(got M={M}, S={S})"
            )
        cycles = self._cycle_tables()
        MC = M * C
        SC = S * C
        D = min(2 * SC - 1, MC)  # circular-buffer depth: peak in-flight inputs

        # total loss weight, known before the loop so every head vjp can be
        # seeded with d(mean_loss)/d(loss_sum_mb) = 1/w_total
        w_total = jax.vmap(self._microbatch_weight)(batch).sum()
        inv_w = 1.0 / jnp.maximum(w_total, 1.0)

        # embedding fwd outside the pp region (plain GSPMD), vjp'd at the end
        embedded, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(lambda mb: self.embed_apply(ep, mb))(batch),
            params["embed"],
        )

        # internal layout is always (C, S, Lc, ...); expand the public C=1
        # layout (S, Lc, ...) outside the shard_map (a free reshape)
        layers_in = (
            jax.tree.map(lambda a: a[None], params["layers"])
            if C == 1
            else params["layers"]
        )

        def pipelined(layers_local, head_params, embedded, batch):
            rank = mesh_lib.compat_axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[:, 0], layers_local)  # (C, Lc, ...)
            is_last = rank == S - 1
            is_first = rank == 0

            x0 = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def head_loss(hp, y, mb_batch):
                lsum, _ = self.head_apply(hp, y, mb_batch)
                return lsum * inv_w

            # remat each layer so the backward slot's vjp stores only per-layer
            # inputs (the scan carries), not every internal residual — same
            # policy as the parent engine's loss_fn
            layer_apply = (
                jax.checkpoint(self.layer_apply)
                if self.remat_layers
                else self.layer_apply
            )
            stage_fn = self._make_stage_fn(layer_apply)

            def chunk_of(tree, k):
                return jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False),
                    tree,
                )

            def cycle(carry, c):
                y_in, cot_in, x_buf, g_layers, g_head, d_emb, loss_sum = carry

                # ---- forward slot ----
                fwd_valid, k_f, mb_f, u_c = self._fwd_slot(c, rank)
                mb_batch = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, mb_f, 0, keepdims=False),
                    batch,
                )
                x_in = jnp.where(
                    is_first & (k_f == 0),
                    lax.dynamic_index_in_dim(embedded, mb_f, 0, keepdims=False),
                    y_in,
                )
                y, aux_f = stage_fn(chunk_of(layers_local, k_f), x_in)

                # Head (lm_head matmul + CE over the vocab) only contributes
                # on the LAST rank's chunk C-1 forward; running it on every
                # rank every cycle is an (S-1)/S FLOP tax at 70B/128k-vocab
                # scale (round-2 weak #4). lax.cond executes one branch at
                # runtime: other ranks/cycles skip the head entirely. This is
                # rank-divergent control flow, but every tp/dp peer of a given
                # pp rank takes the same branch, so the head's internal
                # collectives stay aligned.
                def run_head(operands):
                    hp, yy = operands
                    loss_mb, head_vjp = jax.vjp(
                        lambda h, v: head_loss(h, v, mb_batch), hp, yy
                    )
                    d_head, cot_seed = head_vjp(jnp.ones((), loss_mb.dtype))
                    return loss_mb, d_head, cot_seed

                def skip_head(operands):
                    hp, yy = operands
                    return (
                        jnp.zeros((), jnp.float32),
                        jax.tree.map(jnp.zeros_like, hp),
                        jnp.zeros_like(yy),
                    )

                loss_mb, d_head, cot_seed = lax.cond(
                    fwd_valid & is_last & (k_f == C - 1),
                    run_head,
                    skip_head,
                    (head_params, y),
                )

                slot = jnp.remainder(u_c, D)
                keep = jnp.where(
                    fwd_valid,
                    x_in,
                    lax.dynamic_index_in_dim(x_buf, slot, 0, keepdims=False),
                )
                x_buf = lax.dynamic_update_index_in_dim(x_buf, keep, slot, 0)

                # ---- backward slot: u' = c - (SC-1) - (S-1-rank), mirrored ----
                ub = c - (SC - 1) - (S - 1 - rank)
                bwd_valid = (ub >= 0) & (ub < MC)
                ub_c = jnp.clip(ub, 0, MC - 1)
                k_b = C - 1 - (ub_c % SC) // S
                i_b = ub_c % S
                mb_b = (ub_c // SC) * S + i_b
                u_saved = (ub_c // SC) * SC + k_b * S + i_b
                x_saved = lax.dynamic_index_in_dim(
                    x_buf, jnp.remainder(u_saved, D), 0, keepdims=False
                )
                _, stage_vjp = jax.vjp(
                    stage_fn, chunk_of(layers_local, k_b), x_saved
                )
                cot_y = jnp.where(is_last & (k_b == C - 1), cot_seed, cot_in)
                # aux cotangent: d(loss)/d(aux_slot) = 1/M (the aux term is
                # mean-over-microbatches of pre-weighted scalars)
                aux_cot = jnp.asarray(
                    (1.0 / M) if self.layer_aux else 0.0, jnp.float32
                )
                d_layers_k, dx = stage_vjp((cot_y, aux_cot))

                mask_b = bwd_valid.astype(jnp.float32)
                g_layers = jax.tree.map(
                    lambda acc, g: acc.at[k_b].add(g * mask_b.astype(g.dtype)),
                    g_layers,
                    d_layers_k,
                )
                # head grads/loss already zeroed by the cond gate
                g_head = jax.tree.map(lambda acc, g: acc + g, g_head, d_head)
                loss_sum = loss_sum + loss_mb
                if self.layer_aux:
                    loss_sum = loss_sum + (
                        aux_f * fwd_valid.astype(jnp.float32) / M
                    )

                d_emb_slot = jnp.where(
                    bwd_valid & is_first & (k_b == 0),
                    dx,
                    lax.dynamic_index_in_dim(d_emb, mb_b, 0, keepdims=False),
                )
                d_emb = lax.dynamic_update_index_in_dim(d_emb, d_emb_slot, mb_b, 0)

                if S > 1:
                    # full rotation: rank S-1's chunk-k output wraps to rank
                    # 0's chunk-k+1 input (overridden by the embedding when
                    # the receiving slot is chunk 0)
                    y_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, (i + 1) % S) for i in range(S)]
                    )
                    cot_next = lax.ppermute(
                        dx, mesh_lib.PP_AXIS, [(i, (i - 1) % S) for i in range(S)]
                    )
                else:
                    y_next, cot_next = y, dx
                return (y_next, cot_next, x_buf, g_layers, g_head, d_emb, loss_sum), None

            zeros_like_tree = lambda t: jax.tree.map(jnp.zeros_like, t)  # noqa: E731
            init = (
                x0,
                jnp.zeros_like(x0),
                jnp.zeros((D,) + x0.shape, x0.dtype),
                zeros_like_tree(layers_local),
                zeros_like_tree(head_params),
                jnp.zeros_like(embedded),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, g_layers, g_head, d_emb, loss_sum), _ = lax.scan(
                cycle, init, jnp.arange(cycles)
            )
            # restore the stage dim on layer grads; reduce the rank-local
            # contributions of shared (non-pp) outputs over pp
            g_layers = jax.tree.map(lambda a: a[:, None], g_layers)
            g_head = jax.tree.map(
                lambda a: lax.psum(a, mesh_lib.PP_AXIS), g_head
            )
            from neuronx_distributed_tpu.parallel.collectives import psum_cpu_safe

            d_emb = psum_cpu_safe(d_emb, mesh_lib.PP_AXIS)
            loss_sum = lax.psum(loss_sum, mesh_lib.PP_AXIS)
            return g_layers, g_head, d_emb, loss_sum

        fn = mesh_lib.compat_shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(None, mesh_lib.PP_AXIS), P(), P(), P()),
            out_specs=(P(None, mesh_lib.PP_AXIS), P(), P(), P()),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        g_layers, g_head, d_emb, loss = fn(
            layers_in, params["head"], embedded, batch
        )
        if C == 1:
            g_layers = jax.tree.map(lambda a: a[0], g_layers)
        (g_embed,) = embed_vjp(d_emb)
        grads = {"embed": g_embed, "layers": g_layers, "head": g_head}
        return loss, grads

    def _run_interleaved_forward(self, params, batch):
        """Forward-only interleaved cycle loop (round-4, VERDICT r3 weak #3:
        eval at num_chunks>1 previously paid a full backward; reference
        ``InferenceSchedule`` scheduler.py:144 is forward tasks only).

        Same mixed-radix forward slots as :meth:`value_and_grad`
        (``u = c - rank``), but only ``M·C + S - 1`` cycles (no drain tail),
        no vjp, no input buffer, no remat. The last rank's chunk C-1 outputs
        are collected per microbatch. Returns ``(final (M, mb, ...),
        aux_stacked (S,))`` — the same contract as the parent's
        ``_run_pipeline``."""
        mesh = mesh_lib.get_mesh()
        S, M, C = self._stages(), self.num_microbatches, self.num_chunks
        if M % S != 0:
            raise ValueError(
                f"interleaved pipeline needs microbatches divisible by stages "
                f"(got M={M}, S={S})"
            )
        # asserts the _fwd_slot closed form (shared with this loop) against
        # the SyncTrainInterleavedSchedule task stream — trace-time, host-only
        self._cycle_tables()
        cycles = M * C + S - 1
        embedded = jax.vmap(
            lambda mb: self.embed_apply(params["embed"], mb)
        )(batch)
        layers_in = (
            jax.tree.map(lambda a: a[None], params["layers"])
            if C == 1
            else params["layers"]
        )
        stage_fn = self._make_stage_fn(self.layer_apply)

        def pipelined(layers_local, embedded):
            rank = mesh_lib.compat_axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[:, 0], layers_local)
            is_last = rank == S - 1
            is_first = rank == 0
            x0 = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def chunk_of(tree, k):
                return jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False),
                    tree,
                )

            def cycle(carry, c):
                y_in, out_buf, aux_acc = carry
                fwd_valid, k_f, mb_f, _u_c = self._fwd_slot(c, rank)
                x_in = jnp.where(
                    is_first & (k_f == 0),
                    lax.dynamic_index_in_dim(embedded, mb_f, 0, keepdims=False),
                    y_in,
                )
                y, aux_f = stage_fn(chunk_of(layers_local, k_f), x_in)
                aux_acc = aux_acc + aux_f * fwd_valid.astype(aux_f.dtype)
                collect = fwd_valid & is_last & (k_f == C - 1)
                slot = jnp.where(
                    collect,
                    y,
                    lax.dynamic_index_in_dim(out_buf, mb_f, 0, keepdims=False),
                )
                out_buf = lax.dynamic_update_index_in_dim(out_buf, slot, mb_f, 0)
                if S > 1:
                    y_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, (i + 1) % S) for i in range(S)]
                    )
                else:
                    y_next = y
                return (y_next, out_buf, aux_acc), None

            init = (
                x0,
                jnp.zeros((M,) + x0.shape, x0.dtype),
                jnp.zeros((), jnp.float32),
            )
            (_, out_buf, aux_acc), _ = lax.scan(cycle, init, jnp.arange(cycles))
            return out_buf[None], aux_acc[None]

        fn = mesh_lib.compat_shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(None, mesh_lib.PP_AXIS), P()),
            out_specs=(P(mesh_lib.PP_AXIS), P(mesh_lib.PP_AXIS)),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        out_stacked, aux_stacked = fn(layers_in, embedded)
        return out_stacked[S - 1], aux_stacked

    def forward(self, params, batch, head_fn: Optional[Callable] = None):
        if self.num_chunks == 1:
            return PipelineEngine.forward(self, params, batch, head_fn)
        final, _aux = self._run_interleaved_forward(params, batch)
        if head_fn is not None:
            final = head_fn(params["head"], final)
        return final

    def loss_fn(self, params, batch):
        """Forward-only loss. At num_chunks == 1 the parent scan engine is
        identical math; at num_chunks > 1 the interleaved forward-only cycle
        loop runs — ~3x cheaper than the former value_and_grad-and-discard
        (compiled-FLOPs evidence in tests/pipeline/test_pipeline_model.py)."""
        if self.num_chunks > 1:
            final, aux_stacked = self._run_interleaved_forward(params, batch)
            lsum, wsum = self.head_apply(params["head"], final, batch)
            loss = lsum / jnp.maximum(wsum, 1.0)
            if self.layer_aux:
                loss = loss + aux_stacked.sum() / self.num_microbatches
            return loss
        return PipelineEngine.loss_fn(self, params, batch)


def build_pipeline_engine(schedule: str, num_chunks: int = 1, **engine_kwargs):
    """Schedule-name → engine dispatch shared by every model adapter
    (pipeline/llama.py, gpt_neox.py, mixtral.py): "gpipe" → scan engine,
    "1f1b" → explicit sync 1F1B, "interleaved" → 1F1B with virtual chunks
    (num_chunks < 2 bumped to 2)."""
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if schedule == "gpipe":
        return PipelineEngine(**engine_kwargs)
    if schedule == "interleaved" and num_chunks < 2:
        num_chunks = 2
    return OneFOneBEngine(
        **engine_kwargs,
        num_chunks=num_chunks if schedule == "interleaved" else 1,
    )


def shard_microbatched_batch(batch):
    """Place a microbatched host batch (M, mb, ...): microbatch dim replicated,
    per-microbatch batch dim over dp, sequence over cp."""
    mesh = mesh_lib.get_mesh()

    def put(x):
        spec = [None] * x.ndim
        if x.ndim >= 2:
            spec[1] = mesh_lib.DATA_AXES
        if x.ndim >= 3:
            spec[2] = mesh_lib.CP_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, dict(batch))


def microbatch(batch, num_microbatches: int):
    """(B, ...) → (M, B/M, ...) on every leaf (reference: microbatch dataloader
    wrapping, pipeline/model.py:1955)."""

    def split(a):
        if a.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"batch dim {a.shape[0]} not divisible by {num_microbatches} microbatches"
            )
        return a.reshape((num_microbatches, a.shape[0] // num_microbatches) + a.shape[1:])

    return jax.tree.map(split, batch)

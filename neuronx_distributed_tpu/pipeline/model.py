"""Pipeline-parallel runtime (reference: ``pipeline/model.py`` ``NxDPPModel:80``).

The reference FX-traces the model, partitions the graph, and hand-executes
scheduler task lists per process with send/recv-as-allgather and a mark_step
per task (model.py:1737, comm.py:40). The TPU-native runtime instead compiles
the ENTIRE schedule into one XLA program:

  * ``shard_map`` over ONLY the ``pp`` mesh axis — tp/dp/cp stay "auto", so the
    GSPMD layers (ColumnParallel/RowParallel/...) keep working inside each
    stage and XLA still inserts/overlaps their collectives;
  * stage weights are the scan-stacked layer params reshaped (L,...) →
    (S, L/S, ...) with the stage dim sharded over pp — each rank holds its
    stage's layers;
  * the microbatch loop is a ``lax.scan`` of M + S - 1 ticks; each tick every
    stage applies its layers and passes activations to the next stage with a
    non-wrapping ``lax.ppermute`` (the TPU-native replacement for the
    reference's 2-rank-allgather p2p, pipeline/comm.py:40);
  * backward comes from ``jax.grad`` through the scan: XLA reverses the
    ppermutes, giving the mirrored drain schedule. Per-layer ``jax.checkpoint``
    bounds activation memory (the role 1F1B plays in the reference; here the
    schedule is GPipe-shaped with rematerialized stages — same bubble fraction,
    bounded memory). The pure-Python 1F1B/interleaved task streams live in
    pipeline/scheduler.py as the semantic contract and for an explicitly
    scheduled runtime.

Shared-weight (tied embedding) grad sync (reference model.py:1687) is automatic:
embedding params enter the loss once via stage 0's compute, and autodiff sums
contributions — there is no second copy to reconcile.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class PipelineEngine:
    """Compiles a (embed → scanned layers → head) model into a pp-pipelined
    loss function.

    ``embed_apply(embed_params, mb_batch) -> x``
    ``layer_apply(layer_params, x) -> x``            (one layer; scanned)
    ``head_apply(head_params, x, mb_batch) -> (loss_sum, weight_sum)``
    """

    embed_apply: Callable
    layer_apply: Callable
    head_apply: Callable
    num_layers: int
    num_microbatches: int
    remat_layers: bool = True
    # Per-microbatch loss weight (must equal head_apply's weight_sum for the
    # same microbatch — used by OneFOneBEngine to seed head cotangents before
    # any head has run). Default: loss_mask.sum(), else the label count.
    weight_fn: Optional[Callable] = None

    def _microbatch_weight(self, mb_batch):
        if self.weight_fn is not None:
            return self.weight_fn(mb_batch)
        mask = mb_batch.get("loss_mask")
        if mask is not None:
            return mask.sum().astype(jnp.float32)
        return jnp.asarray(float(mb_batch["labels"].size), jnp.float32)

    def _stages(self) -> int:
        return mesh_lib.get_pipeline_model_parallel_size()

    # --- param layout ---------------------------------------------------------

    def stack_layer_specs(self, layer_specs):
        """(L, ...) per-layer specs → (S, L/S, ...) with pp on the stage dim."""

        def fix(spec):
            entries = list(spec)
            # leading dim is the stacked layer dim: becomes (pp, layers/stage)
            rest = entries[1:] if entries else []
            return P(mesh_lib.PP_AXIS, None, *rest)

        return jax.tree.map(fix, layer_specs, is_leaf=lambda s: isinstance(s, P))

    def reshape_layer_params(self, layer_params):
        """Physically reshape stacked layer leaves (L, ...) → (S, L/S, ...)."""
        S = self._stages()
        L = self.num_layers
        if L % S != 0:
            raise ValueError(f"num_layers {L} not divisible by {S} pipeline stages")

        def reshape(a):
            return a.reshape((S, L // S) + a.shape[1:])

        return jax.tree.map(reshape, layer_params)

    def unshape_layer_params(self, layer_params):
        def reshape(a):
            return a.reshape((self.num_layers,) + a.shape[2:])

        return jax.tree.map(reshape, layer_params)

    # --- the pipelined loss ---------------------------------------------------

    def loss_fn(self, params, batch):
        """params = {"embed":…, "layers": (S, L/S, …) leaves, "head":…};
        batch leaves shaped (M, mb, ...). Returns mean loss.

        Only embed + layers run inside the pp-manual region; the head runs
        OUTSIDE in plain GSPMD on the last stage's collected outputs. (Besides
        being cleaner, this sidesteps an XLA SPMD-partitioner CHECK crash —
        spmd_partitioner_util.cc:495, jaxlib 0.9 — triggered by lax.cond
        branches touching sharded operands inside a partial-manual shard_map.)
        XLA slices the collected-output tensor at the last stage, so only that
        stage's activations move."""
        mesh = mesh_lib.get_mesh()
        S = self._stages()
        M = self.num_microbatches
        layer_apply = (
            jax.checkpoint(self.layer_apply) if self.remat_layers else self.layer_apply
        )

        def stage_fn(layers_local, x):
            def body(h, one_layer):
                return layer_apply(one_layer, h), None

            out, _ = lax.scan(body, x, layers_local)
            return out

        def pipelined(layers_local, embed_params, batch):
            rank = lax.axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[0], layers_local)  # drop stage dim
            # Embed all M microbatches once, OUTSIDE the tick loop: the loop
            # otherwise pays M+S-1 embedding fwd (and bwd) passes per stage for
            # the M that are used, and the differentiated scan grows with it.
            embedded = jax.vmap(lambda mb: self.embed_apply(embed_params, mb))(batch)
            buf = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def tick(buf, t):
                mb_in = jnp.clip(t, 0, M - 1)
                x_in = lax.dynamic_index_in_dim(embedded, mb_in, 0, keepdims=False)
                x = jnp.where(rank == 0, x_in, buf)
                y = stage_fn(layers_local, x)
                if S > 1:
                    buf_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, i + 1) for i in range(S - 1)]
                    )
                else:
                    buf_next = y
                return buf_next, y

            _, ys = lax.scan(tick, buf, jnp.arange(M + S - 1))
            return ys  # (M+S-1, mb, ...): this rank's stage outputs per tick

        fn = jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(mesh_lib.PP_AXIS), P(), P()),
            out_specs=P(mesh_lib.PP_AXIS),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        ys = fn(params["layers"], params["embed"], batch)
        # (S·(M+S-1), mb, ...) → last stage's valid window = microbatch outputs
        ticks = M + S - 1
        ys = ys.reshape((S, ticks) + ys.shape[1:])
        final = ys[S - 1, S - 1 :]  # (M, mb, ...)
        lsum, wsum = self.head_apply(params["head"], final, batch)
        return lsum / jnp.maximum(wsum, 1.0)


@dataclasses.dataclass
class OneFOneBEngine(PipelineEngine):
    """Explicitly-scheduled synchronous 1F1B runtime (VERDICT.md missing #2;
    reference ``pipeline/model.py:1737`` ``_exec_schedule`` over
    ``Train1F1BSchedule``).

    Unlike :class:`PipelineEngine` (scan-GPipe: one forward scan, backward by
    ``jax.grad`` reversing it, activation memory O(M) stage-inputs under
    remat), this engine *is* the scheduler: grads are computed inside the
    cycle loop, never by differentiating it. Each cycle rank r

      * forwards microbatch  ``c - r``            (recv → stage → send via
        ``ppermute``, storing only the stage INPUT in a depth-``2S-1``
        circular buffer),
      * backwards microbatch ``c - 2(S-1) + r``   (pop the saved input,
        ``jax.vjp`` recomputes the stage forward and pulls the cotangent
        back, accumulate param grads, send the input-cotangent upstream),

    which is ``SyncTrain1F1BSchedule`` — 1F1B's dependency structure in SPMD
    lockstep (see its docstring for the warmup/bubble accounting). Activation
    memory is O(S) stage-inputs, independent of M: the scan-GPipe engine
    stores M+S-1 stage inputs, this one ``min(2S-1, M)``. Compute per
    microbatch is identical (both pay the remat 4/3: fwd + vjp-recompute-fwd
    + bwd).

    The loss head runs inside the loop on every rank (only the last rank's
    result is kept — rank-divergent module calls cannot be expressed in one
    SPMD program without doubling the traced graph under ``lax.cond``); the
    embedding fwd/bwd runs outside in plain GSPMD, connected through an
    explicit (M, ...) cotangent buffer.
    """

    def _cycle_tables(self):
        """Per-rank (fwd_mb, bwd_mb) per cycle, derived from the task stream
        of SyncTrain1F1BSchedule — the scheduler is the source of truth; the
        closed forms inside the scan body are asserted against it here."""
        from neuronx_distributed_tpu.pipeline.scheduler import (
            BackwardTask,
            ForwardTask,
            SyncTrain1F1BSchedule,
            validate_schedule,
        )

        S, M = self._stages(), self.num_microbatches
        cycles = M + 2 * (S - 1)
        for r in range(S):
            sched = SyncTrain1F1BSchedule(M, S, r)
            validate_schedule(sched)
            fwd = [t.mb for t in sched.steps() if isinstance(t, ForwardTask)]
            bwd = [t.mb for t in sched.steps() if isinstance(t, BackwardTask)]
            want_fwd = [c - r for c in range(cycles) if 0 <= c - r < M]
            want_bwd = [
                c - 2 * (S - 1) + r
                for c in range(cycles)
                if 0 <= c - 2 * (S - 1) + r < M
            ]
            if fwd != want_fwd or bwd != want_bwd:
                raise AssertionError(
                    f"1F1B cycle tables diverge from SyncTrain1F1BSchedule at rank {r}"
                )
        return cycles

    def value_and_grad(self, params, batch):
        """(loss, grads) with grads computed by the explicit 1F1B schedule.
        Same params/batch layout as :meth:`PipelineEngine.loss_fn`."""
        mesh = mesh_lib.get_mesh()
        S = self._stages()
        M = self.num_microbatches
        cycles = self._cycle_tables()
        D = min(2 * S - 1, M)  # circular-buffer depth: peak in-flight inputs

        # total loss weight, known before the loop so every head vjp can be
        # seeded with d(mean_loss)/d(loss_sum_mb) = 1/w_total
        w_total = jax.vmap(self._microbatch_weight)(batch).sum()
        inv_w = 1.0 / jnp.maximum(w_total, 1.0)

        # embedding fwd outside the pp region (plain GSPMD), vjp'd at the end
        embedded, embed_vjp = jax.vjp(
            lambda ep: jax.vmap(lambda mb: self.embed_apply(ep, mb))(batch),
            params["embed"],
        )

        def pipelined(layers_local, head_params, embedded, batch):
            rank = lax.axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[0], layers_local)
            is_last = rank == S - 1
            is_first = rank == 0

            x0 = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def head_loss(hp, y, mb_batch):
                lsum, _ = self.head_apply(hp, y, mb_batch)
                return lsum * inv_w

            # remat each layer so the backward slot's vjp stores only per-layer
            # inputs (the scan carries), not every internal residual — same
            # policy as the parent engine's loss_fn
            layer_apply = (
                jax.checkpoint(self.layer_apply)
                if self.remat_layers
                else self.layer_apply
            )

            def stage_fn(lp, x):
                def body(h, one_layer):
                    return layer_apply(one_layer, h), None

                out, _ = lax.scan(body, x, lp)
                return out

            def cycle(carry, c):
                y_in, cot_in, x_buf, g_layers, g_head, d_emb, loss_sum = carry

                # ---- forward slot: mb = c - rank ----
                mf = c - rank
                fwd_valid = (mf >= 0) & (mf < M)
                mf_c = jnp.clip(mf, 0, M - 1)
                mb_batch = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, mf_c, 0, keepdims=False),
                    batch,
                )
                x_in = jnp.where(
                    is_first,
                    lax.dynamic_index_in_dim(embedded, mf_c, 0, keepdims=False),
                    y_in,
                )
                y = stage_fn(layers_local, x_in)
                loss_mb, head_vjp = jax.vjp(
                    lambda hp, yy: head_loss(hp, yy, mb_batch), head_params, y
                )
                d_head, cot_seed = head_vjp(jnp.ones((), loss_mb.dtype))

                slot = jnp.remainder(mf_c, D)
                keep = jnp.where(
                    fwd_valid,
                    x_in,
                    lax.dynamic_index_in_dim(x_buf, slot, 0, keepdims=False),
                )
                x_buf = lax.dynamic_update_index_in_dim(x_buf, keep, slot, 0)

                # ---- backward slot: mb = c - 2(S-1) + rank ----
                mb_i = c - 2 * (S - 1) + rank
                bwd_valid = (mb_i >= 0) & (mb_i < M)
                mb_c = jnp.clip(mb_i, 0, M - 1)
                x_saved = lax.dynamic_index_in_dim(
                    x_buf, jnp.remainder(mb_c, D), 0, keepdims=False
                )
                _, stage_vjp = jax.vjp(stage_fn, layers_local, x_saved)
                cot_y = jnp.where(is_last, cot_seed, cot_in)
                d_layers, dx = stage_vjp(cot_y)

                mask_b = bwd_valid.astype(jnp.float32)
                g_layers = jax.tree.map(
                    lambda acc, g: acc + g * mask_b.astype(g.dtype), g_layers, d_layers
                )
                mask_h = (fwd_valid & is_last).astype(jnp.float32)
                g_head = jax.tree.map(
                    lambda acc, g: acc + g * mask_h.astype(g.dtype), g_head, d_head
                )
                loss_sum = loss_sum + loss_mb * mask_h.astype(loss_mb.dtype)

                d_emb_slot = jnp.where(
                    bwd_valid & is_first,
                    dx,
                    lax.dynamic_index_in_dim(d_emb, mb_c, 0, keepdims=False),
                )
                d_emb = lax.dynamic_update_index_in_dim(d_emb, d_emb_slot, mb_c, 0)

                if S > 1:
                    y_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, i + 1) for i in range(S - 1)]
                    )
                    cot_next = lax.ppermute(
                        dx, mesh_lib.PP_AXIS, [(i, i - 1) for i in range(1, S)]
                    )
                else:
                    y_next, cot_next = y, dx
                return (y_next, cot_next, x_buf, g_layers, g_head, d_emb, loss_sum), None

            zeros_like_tree = lambda t: jax.tree.map(jnp.zeros_like, t)  # noqa: E731
            init = (
                x0,
                jnp.zeros_like(x0),
                jnp.zeros((D,) + x0.shape, x0.dtype),
                zeros_like_tree(layers_local),
                zeros_like_tree(head_params),
                jnp.zeros_like(embedded),
                jnp.zeros((), jnp.float32),
            )
            (_, _, _, g_layers, g_head, d_emb, loss_sum), _ = lax.scan(
                cycle, init, jnp.arange(cycles)
            )
            # restore the stage dim on layer grads; reduce the rank-local
            # contributions of shared (non-pp) outputs over pp
            g_layers = jax.tree.map(lambda a: a[None], g_layers)
            g_head = jax.tree.map(
                lambda a: lax.psum(a, mesh_lib.PP_AXIS), g_head
            )
            d_emb = lax.psum(d_emb, mesh_lib.PP_AXIS)
            loss_sum = lax.psum(loss_sum, mesh_lib.PP_AXIS)
            return g_layers, g_head, d_emb, loss_sum

        fn = jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(mesh_lib.PP_AXIS), P(), P(), P()),
            out_specs=(P(mesh_lib.PP_AXIS), P(), P(), P()),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        g_layers, g_head, d_emb, loss = fn(
            params["layers"], params["head"], embedded, batch
        )
        (g_embed,) = embed_vjp(d_emb)
        grads = {"embed": g_embed, "layers": g_layers, "head": g_head}
        return loss, grads

    def loss_fn(self, params, batch):
        """Forward-only loss via the parent scan engine (identical math); the
        1F1B machinery matters only for the backward."""
        return PipelineEngine.loss_fn(self, params, batch)


def shard_microbatched_batch(batch):
    """Place a microbatched host batch (M, mb, ...): microbatch dim replicated,
    per-microbatch batch dim over dp, sequence over cp."""
    mesh = mesh_lib.get_mesh()

    def put(x):
        spec = [None] * x.ndim
        if x.ndim >= 2:
            spec[1] = mesh_lib.DATA_AXES
        if x.ndim >= 3:
            spec[2] = mesh_lib.CP_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, dict(batch))


def microbatch(batch, num_microbatches: int):
    """(B, ...) → (M, B/M, ...) on every leaf (reference: microbatch dataloader
    wrapping, pipeline/model.py:1955)."""

    def split(a):
        if a.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"batch dim {a.shape[0]} not divisible by {num_microbatches} microbatches"
            )
        return a.reshape((num_microbatches, a.shape[0] // num_microbatches) + a.shape[1:])

    return jax.tree.map(split, batch)

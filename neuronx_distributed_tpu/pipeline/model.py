"""Pipeline-parallel runtime (reference: ``pipeline/model.py`` ``NxDPPModel:80``).

The reference FX-traces the model, partitions the graph, and hand-executes
scheduler task lists per process with send/recv-as-allgather and a mark_step
per task (model.py:1737, comm.py:40). The TPU-native runtime instead compiles
the ENTIRE schedule into one XLA program:

  * ``shard_map`` over ONLY the ``pp`` mesh axis — tp/dp/cp stay "auto", so the
    GSPMD layers (ColumnParallel/RowParallel/...) keep working inside each
    stage and XLA still inserts/overlaps their collectives;
  * stage weights are the scan-stacked layer params reshaped (L,...) →
    (S, L/S, ...) with the stage dim sharded over pp — each rank holds its
    stage's layers;
  * the microbatch loop is a ``lax.scan`` of M + S - 1 ticks; each tick every
    stage applies its layers and passes activations to the next stage with a
    non-wrapping ``lax.ppermute`` (the TPU-native replacement for the
    reference's 2-rank-allgather p2p, pipeline/comm.py:40);
  * backward comes from ``jax.grad`` through the scan: XLA reverses the
    ppermutes, giving the mirrored drain schedule. Per-layer ``jax.checkpoint``
    bounds activation memory (the role 1F1B plays in the reference; here the
    schedule is GPipe-shaped with rematerialized stages — same bubble fraction,
    bounded memory). The pure-Python 1F1B/interleaved task streams live in
    pipeline/scheduler.py as the semantic contract and for an explicitly
    scheduled runtime.

Shared-weight (tied embedding) grad sync (reference model.py:1687) is automatic:
embedding params enter the loss once via stage 0's compute, and autodiff sums
contributions — there is no second copy to reconcile.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class PipelineEngine:
    """Compiles a (embed → scanned layers → head) model into a pp-pipelined
    loss function.

    ``embed_apply(embed_params, mb_batch) -> x``
    ``layer_apply(layer_params, x) -> x``            (one layer; scanned)
    ``head_apply(head_params, x, mb_batch) -> (loss_sum, weight_sum)``
    """

    embed_apply: Callable
    layer_apply: Callable
    head_apply: Callable
    num_layers: int
    num_microbatches: int
    remat_layers: bool = True

    def _stages(self) -> int:
        return mesh_lib.get_pipeline_model_parallel_size()

    # --- param layout ---------------------------------------------------------

    def stack_layer_specs(self, layer_specs):
        """(L, ...) per-layer specs → (S, L/S, ...) with pp on the stage dim."""

        def fix(spec):
            entries = list(spec)
            # leading dim is the stacked layer dim: becomes (pp, layers/stage)
            rest = entries[1:] if entries else []
            return P(mesh_lib.PP_AXIS, None, *rest)

        return jax.tree.map(fix, layer_specs, is_leaf=lambda s: isinstance(s, P))

    def reshape_layer_params(self, layer_params):
        """Physically reshape stacked layer leaves (L, ...) → (S, L/S, ...)."""
        S = self._stages()
        L = self.num_layers
        if L % S != 0:
            raise ValueError(f"num_layers {L} not divisible by {S} pipeline stages")

        def reshape(a):
            return a.reshape((S, L // S) + a.shape[1:])

        return jax.tree.map(reshape, layer_params)

    def unshape_layer_params(self, layer_params):
        def reshape(a):
            return a.reshape((self.num_layers,) + a.shape[2:])

        return jax.tree.map(reshape, layer_params)

    # --- the pipelined loss ---------------------------------------------------

    def loss_fn(self, params, batch):
        """params = {"embed":…, "layers": (S, L/S, …) leaves, "head":…};
        batch leaves shaped (M, mb, ...). Returns mean loss.

        Only embed + layers run inside the pp-manual region; the head runs
        OUTSIDE in plain GSPMD on the last stage's collected outputs. (Besides
        being cleaner, this sidesteps an XLA SPMD-partitioner CHECK crash —
        spmd_partitioner_util.cc:495, jaxlib 0.9 — triggered by lax.cond
        branches touching sharded operands inside a partial-manual shard_map.)
        XLA slices the collected-output tensor at the last stage, so only that
        stage's activations move."""
        mesh = mesh_lib.get_mesh()
        S = self._stages()
        M = self.num_microbatches
        layer_apply = (
            jax.checkpoint(self.layer_apply) if self.remat_layers else self.layer_apply
        )

        def stage_fn(layers_local, x):
            def body(h, one_layer):
                return layer_apply(one_layer, h), None

            out, _ = lax.scan(body, x, layers_local)
            return out

        def pipelined(layers_local, embed_params, batch):
            rank = lax.axis_index(mesh_lib.PP_AXIS)
            layers_local = jax.tree.map(lambda a: a[0], layers_local)  # drop stage dim
            # Embed all M microbatches once, OUTSIDE the tick loop: the loop
            # otherwise pays M+S-1 embedding fwd (and bwd) passes per stage for
            # the M that are used, and the differentiated scan grows with it.
            embedded = jax.vmap(lambda mb: self.embed_apply(embed_params, mb))(batch)
            buf = jnp.zeros_like(jax.tree.map(lambda a: a[0], embedded))

            def tick(buf, t):
                mb_in = jnp.clip(t, 0, M - 1)
                x_in = lax.dynamic_index_in_dim(embedded, mb_in, 0, keepdims=False)
                x = jnp.where(rank == 0, x_in, buf)
                y = stage_fn(layers_local, x)
                if S > 1:
                    buf_next = lax.ppermute(
                        y, mesh_lib.PP_AXIS, [(i, i + 1) for i in range(S - 1)]
                    )
                else:
                    buf_next = y
                return buf_next, y

            _, ys = lax.scan(tick, buf, jnp.arange(M + S - 1))
            return ys  # (M+S-1, mb, ...): this rank's stage outputs per tick

        fn = jax.shard_map(
            pipelined,
            mesh=mesh,
            in_specs=(P(mesh_lib.PP_AXIS), P(), P()),
            out_specs=P(mesh_lib.PP_AXIS),
            check_vma=False,
            axis_names={mesh_lib.PP_AXIS},
        )
        ys = fn(params["layers"], params["embed"], batch)
        # (S·(M+S-1), mb, ...) → last stage's valid window = microbatch outputs
        ticks = M + S - 1
        ys = ys.reshape((S, ticks) + ys.shape[1:])
        final = ys[S - 1, S - 1 :]  # (M, mb, ...)
        lsum, wsum = self.head_apply(params["head"], final, batch)
        return lsum / jnp.maximum(wsum, 1.0)


def shard_microbatched_batch(batch):
    """Place a microbatched host batch (M, mb, ...): microbatch dim replicated,
    per-microbatch batch dim over dp, sequence over cp."""
    mesh = mesh_lib.get_mesh()

    def put(x):
        spec = [None] * x.ndim
        if x.ndim >= 2:
            spec[1] = mesh_lib.DATA_AXES
        if x.ndim >= 3:
            spec[2] = mesh_lib.CP_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, dict(batch))


def microbatch(batch, num_microbatches: int):
    """(B, ...) → (M, B/M, ...) on every leaf (reference: microbatch dataloader
    wrapping, pipeline/model.py:1955)."""

    def split(a):
        if a.shape[0] % num_microbatches != 0:
            raise ValueError(
                f"batch dim {a.shape[0]} not divisible by {num_microbatches} microbatches"
            )
        return a.reshape((num_microbatches, a.shape[0] // num_microbatches) + a.shape[1:])

    return jax.tree.map(split, batch)

"""Deterministic fault injection for the training loop.

The serving engine's chaos harness (``serving/faults.py``) proved the
pattern: failures must be *schedulable*, not random, so a test can assert
the exact recovery path ran. This is the training-side twin, consulted by
``Trainer.fit`` through hooks that are no-ops when no injector is attached
— the hot path is untouched by default.

Injection points (step/attempt indices are 0-based and deterministic):

* ``nan_loss(at=k, times=t)`` — the k-th..(k+t-1)-th steps' batches are
  corrupted so the REAL loss math produces NaN: every ``loss_mask`` entry
  becomes NaN (``default_loss_fn``'s masked mean propagates it). The
  on-device anomaly guard must catch it — params/opt-state unchanged,
  training continues. Batches without a ``loss_mask`` get one injected
  (all-NaN); note that changes the batch pytree and costs one retrace, so
  chaos workloads that also assert compile counts should carry a mask
  throughout (``PackedCorpus`` with segments does).
* ``spike_grads(at=k, times=t, factor=1e6)`` — scales the k-th step's
  ``loss_mask`` by ``-factor``: a positive scale would cancel in the
  masked mean's denominator, but a negative sum drives
  ``max(mask.sum(), 1)`` to its clamp branch, so the loss (and every
  gradient) scales by the full factor — finite, huge, exactly what the
  grad-norm spike detector must catch.
* ``fail_dispatch(at=j, times=t)`` — the j-th..(j+t-1)-th train-step
  *dispatch attempts* raise ``InjectedDispatchError`` before the jitted
  step runs (donated buffers NOT consumed — a host-side enqueue failure,
  the recoverable case). ``times=None`` fails every attempt from ``j`` on:
  the way to drive the trainer into HALTED.
* ``corrupt_checkpoint(tag)`` — deletes ``tag``'s ``done`` marker right
  after its save commits, simulating a run killed mid-save; drives the
  ``load_checkpoint`` newest-pointer fallback.
* ``flip_bits(target=..., at=k, times=t, device=d)`` — SILENT data
  corruption (ISSUE 20): flips ONE low-order bit, so loud guards (NaN,
  grad spikes) never fire and only the integrity sentinel's bit-level
  fingerprints can catch it. ``target="params"``/``"opt_state"`` corrupts
  the live TrainState right after step k's dispatch — ``device=d``
  corrupts ONE device's copy of the first leaf (the broken-replication
  model the dp vote localizes), ``device=None`` corrupts every copy (the
  solo canary's uniform model). ``target="checkpoint_shard"`` flips one
  byte of the largest committed payload file of the k-th..(k+t-1)-th
  checkpoint saves — the post-commit storage rot the restore-time
  manifest verification must reject.
* ``deliver_sigterm(at=k)`` — delivers a REAL ``SIGTERM`` to this process
  at the start of step k (``os.kill``), so the graceful-preemption path is
  tested through the actual signal handler, not a simulation.

``counters`` records every fault actually fired so chaos tests can assert
the schedule ran (an injection that never fired proves nothing).
"""

from __future__ import annotations

import os
import signal
from typing import Dict, List, Optional, Set, Tuple


class InjectedFault(RuntimeError):
    """Base class for injected training failures (never raised by real
    code paths)."""


class InjectedDispatchError(InjectedFault):
    """Scheduled train-step dispatch failure."""


class FaultInjector:
    """Schedule-driven fault source consulted by ``Trainer.fit`` hooks."""

    def __init__(self):
        # [at, end) half-open step/attempt windows; end=None → open-ended
        self._nan_windows: List[Tuple[int, Optional[int]]] = []
        self._spike_windows: List[Tuple[int, Optional[int], float]] = []
        self._dispatch_windows: List[Tuple[int, Optional[int]]] = []
        self._corrupt_tags: Set[str] = set()
        self._sigterm_steps: Set[int] = set()
        # flip_bits schedules: state targets keyed by step window, shard
        # target keyed by save-event window (0-based count of commits)
        # mutable [at, end, target, device, remaining_fires] entries —
        # see flip_bits for why the budget slot exists
        self._flip_state_windows: List[list] = []
        self._flip_shard_windows: List[Tuple[int, Optional[int]]] = []
        self._saves_seen = 0
        self.counters: Dict[str, int] = {
            "nan_losses": 0,
            "spiked_grads": 0,
            "dispatch_failures": 0,
            "corrupted_checkpoints": 0,
            "sigterms": 0,
            "bit_flips": 0,
        }

    # --- schedule construction ----------------------------------------------

    def nan_loss(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        end = None if times is None else at + times
        self._nan_windows.append((at, end))
        return self

    def spike_grads(self, at: int = 0, times: Optional[int] = 1,
                    factor: float = 1e6) -> "FaultInjector":
        end = None if times is None else at + times
        self._spike_windows.append((at, end, factor))
        return self

    def fail_dispatch(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        end = None if times is None else at + times
        self._dispatch_windows.append((at, end))
        return self

    def corrupt_checkpoint(self, tag: str) -> "FaultInjector":
        self._corrupt_tags.add(tag)
        return self

    def flip_bits(self, target: str, at: int = 0,
                  times: Optional[int] = 1,
                  device: Optional[int] = None) -> "FaultInjector":
        """Schedule single-bit flips. ``target`` is one of ``params``,
        ``opt_state`` (live-state flips at the ``at``-th..(at+times-1)-th
        steps, right after dispatch; ``device`` selects one device's copy,
        None flips every copy) or ``checkpoint_shard`` (post-commit byte
        flip of the ``at``-th..(at+times-1)-th checkpoint saves)."""
        end = None if times is None else at + times
        if target in ("params", "opt_state"):
            # mutable entry: the trailing slot is the remaining-fires
            # budget. A bit flip models a transient physical event, not a
            # property of the step index — when the sentinel rolls the run
            # back and the window's steps re-train, an exhausted schedule
            # must NOT re-corrupt them (None = unlimited, the soak mode)
            self._flip_state_windows.append([at, end, target, device, times])
        elif target == "checkpoint_shard":
            self._flip_shard_windows.append((at, end))
        else:
            raise ValueError(
                f"flip_bits target must be params|opt_state|"
                f"checkpoint_shard, got {target!r}"
            )
        return self

    def deliver_sigterm(self, at: int) -> "FaultInjector":
        self._sigterm_steps.add(at)
        return self

    def pending_corruption(self, tag: str) -> bool:
        """True when a ``corrupt_checkpoint`` is scheduled for ``tag`` —
        the save path drains async commits first so the corruption hits a
        checkpoint that actually exists (see ``on_checkpoint_saved``)."""
        return tag in self._corrupt_tags

    def pending_shard_flip(self) -> bool:
        """True when a ``flip_bits("checkpoint_shard")`` window covers the
        NEXT committed save — same async-drain contract as
        ``pending_corruption``: the flip must land on committed,
        manifested bytes, not race the background commit."""
        return self._hit(self._flip_shard_windows, self._saves_seen)

    # --- trainer hooks -------------------------------------------------------

    @staticmethod
    def _hit(windows, index: int) -> bool:
        return any(
            index >= at and (end is None or index < end)
            for at, end in windows
        )

    def on_step_start(self, step: int) -> None:
        """Called with the 0-based global step index before the batch is
        prepared. Delivers a scheduled REAL SIGTERM (the graceful-preemption
        handler is under test, not a stand-in)."""
        if step in self._sigterm_steps:
            self._sigterm_steps.discard(step)
            self.counters["sigterms"] += 1
            os.kill(os.getpid(), signal.SIGTERM)

    def corrupt_batch(self, step: int, batch: dict) -> dict:
        """Called with the host batch for ``step``; returns the (possibly
        corrupted) batch. NaN injection poisons ``loss_mask`` so the real
        masked-mean loss math produces NaN; spikes scale it so gradients
        blow up finitely."""
        import numpy as np

        nan = self._hit(self._nan_windows, step)
        spike = next(
            (f for at, end, f in self._spike_windows
             if step >= at and (end is None or step < end)),
            None,
        )
        if not nan and spike is None:
            return batch
        batch = dict(batch)
        mask = batch.get("loss_mask")
        if mask is None:
            mask = np.ones_like(
                np.asarray(batch["input_ids"]), dtype=np.float32
            )
        else:
            mask = np.array(mask, dtype=np.float32, copy=True)
        if nan:
            mask[...] = np.nan
            self.counters["nan_losses"] += 1
        elif spike is not None:
            # numerator-only scaling (see module docstring): the negative
            # mask sum lands in the masked mean's max(sum, 1) clamp, so
            # gradients scale by the full |factor| while staying finite
            mask *= -abs(spike)
            self.counters["spiked_grads"] += 1
        batch["loss_mask"] = mask
        return batch

    def on_state(self, step: int, state):
        """Called with the live TrainState right after step ``step``'s
        dispatch (before the sentinel's check): a scheduled flip corrupts
        the first leaf of params/opt-state by one low-order bit — the
        silent model no loss/grad guard can see. Returns the (possibly
        corrupted) state; a no-op unless a schedule hits."""
        hits = []
        for w in self._flip_state_windows:
            at, end, tgt, dev, left = w
            if step < at or (end is not None and step >= end) or left == 0:
                continue
            if left is not None:
                w[4] = left - 1
            hits.append((tgt, dev))
        if not hits:
            return state
        from neuronx_distributed_tpu.integrity.chaos import flip_tree_bit

        for target, device in hits:
            if target == "params":
                state = state.replace(
                    params=flip_tree_bit(state.params, device_index=device)
                )
            else:
                state = state.replace(
                    opt_state=flip_tree_bit(
                        state.opt_state, device_index=device
                    )
                )
            self.counters["bit_flips"] += 1
        return state

    def on_dispatch(self, attempt: int) -> None:
        """Called with the 0-based dispatch ATTEMPT index (failed attempts
        count, so a retry schedule is deterministic). Raises when the
        schedule says this attempt fails — BEFORE the jitted step runs, so
        donated buffers survive (the recoverable host-side case)."""
        if self._hit(self._dispatch_windows, attempt):
            self.counters["dispatch_failures"] += 1
            raise InjectedDispatchError(
                f"injected train-step dispatch failure at attempt {attempt}"
            )

    def on_checkpoint_saved(self, checkpoint_dir: str, tag: str) -> None:
        """Called after a checkpoint for ``tag`` commits. A scheduled
        ``corrupt_checkpoint`` deletes its ``done`` marker — the on-disk
        state of a run killed between the tensor flush and the marker
        write. A scheduled ``flip_bits("checkpoint_shard")`` instead flips
        one byte of the committed payload — storage rot AFTER a clean
        commit, which only the integrity manifest can catch."""
        shard_hit = self._hit(self._flip_shard_windows, self._saves_seen)
        if tag not in self._corrupt_tags and not shard_hit:
            self._saves_seen += 1
            return
        from neuronx_distributed_tpu.trainer.checkpoint import (
            DONE_MARKER,
            _ITEMS_DIRNAME,
            create_checkpoint_storage,
        )

        storage = create_checkpoint_storage(checkpoint_dir)
        marker = os.path.join(tag, DONE_MARKER)
        if not storage.file_exists(marker):
            # the save has not committed yet (async in flight) — leave the
            # schedule armed rather than "corrupting" nothing and letting
            # the background commit write a pristine marker afterwards
            return
        self._saves_seen += 1
        if tag in self._corrupt_tags:
            self._corrupt_tags.discard(tag)
            storage.remove_file(marker)
            self.counters["corrupted_checkpoints"] += 1
        if shard_hit:
            # flip one byte in the LARGEST payload file — the actual
            # tensor bytes, not a tiny metadata json
            items = os.path.join(tag, _ITEMS_DIRNAME)
            files = storage.list_files(items)
            if files:
                victim = max(
                    files,
                    key=lambda f: len(storage.load_bytes(os.path.join(items, f))),
                )
                path = os.path.join(items, victim)
                raw = bytearray(storage.load_bytes(path))
                raw[len(raw) // 2] ^= 0x01
                storage.save_bytes(bytes(raw), path)
                self.counters["bit_flips"] += 1

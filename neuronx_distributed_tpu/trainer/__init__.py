from neuronx_distributed_tpu.trainer.trainer import (
    AnomalyGuardConfig,
    OptimizerConfig,
    TrainingConfig,
    TrainState,
    build_train_step,
    create_train_state,
    init_anomaly_guard_state,
    initialize_parallel_model,
    initialize_parallel_optimizer,
    make_optimizer,
    neuronx_distributed_tpu_config,
    shard_batch,
)

__all__ = [
    "AnomalyGuardConfig",
    "OptimizerConfig",
    "TrainingConfig",
    "TrainState",
    "build_train_step",
    "create_train_state",
    "init_anomaly_guard_state",
    "initialize_parallel_model",
    "initialize_parallel_optimizer",
    "make_optimizer",
    "neuronx_distributed_tpu_config",
    "shard_batch",
]

"""High-level training API (reference: ``trainer/trainer.py``
``neuronx_distributed_config:32``, ``initialize_parallel_model:147``,
``initialize_parallel_optimizer:237`` and ``trainer/optimizer.py``
``NxDOptimizer``).

The reference's phases — config normalization → parallel-state init → model
materialization on the right device → optimizer wrapping with zero-1 plumbing →
NxDOptimizer.step orchestrating CP/SP grad reductions, bucketed DP all-reduce,
clip, inner step — collapse on TPU into: build config, jit-init sharded params,
device_put a zero-1-sharded optax state, and one jitted train step whose
autodiff + sharding annotations produce all of those collectives. The optimizer
"wrapper" is the train-step builder; grad-norm is returned as a metric exactly
like ``NxDOptimizer.grad_norm`` (trainer/optimizer.py:27).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Mapping, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.core import meta
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.optim.zero1 import zero1_shardings_for_opt_state
from neuronx_distributed_tpu.parallel import mesh as mesh_lib
from neuronx_distributed_tpu.parallel.grads import clip_grad_norm
from neuronx_distributed_tpu.parallel.losses import parallel_cross_entropy
from neuronx_distributed_tpu.parallel.sharding import param_shardings
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference defaults: zero-1 on, grad clipping at 1.0
    (trainer/trainer.py:60-90)."""

    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    beta1: float = 0.9
    beta2: float = 0.95
    eps: float = 1e-8
    max_grad_norm: float = 1.0
    zero1: bool = True
    # >1: split each batch into this many microbatches and accumulate grads
    # inside the jitted step (reference: the grad-accum loop of
    # run_llama_nxd.py / Lightning accumulate_grad_batches)
    grad_accum_steps: int = 1
    warmup_steps: int = 0
    lr_schedule: str = "constant"  # constant | cosine
    total_steps: int = 10000
    min_lr_ratio: float = 0.1


@dataclasses.dataclass(frozen=True)
class AnomalyGuardConfig:
    """On-device anomaly detection for the jitted train step (reference
    analogue: the reference's loss-NaN guards around NxDOptimizer.step;
    here the *skip decision* lives inside the step so the clean path pays
    no host round-trip).

    A step is anomalous when its loss or pre-clip grad norm is non-finite,
    or — after ``warmup_steps`` good steps have warmed the running norm —
    when the pre-clip grad norm exceeds ``spike_factor ×`` the EMA of past
    good steps' norms. Anomalous steps keep params AND optimizer state
    bit-identical (per-leaf ``where`` select on device); the EMA only
    learns from good steps, so one spike cannot normalize the next.

    ``budget`` bounds TOTAL skipped steps on the host side: exceeding it
    halts training with an emergency checkpoint instead of silently
    skipping forever (``None`` = unbounded). The guard carry — EMA, warmup
    count, and the device skips counter the budget reads — rides every
    checkpoint, so a resumed run detects and budgets exactly as the
    uninterrupted run would (preemption cycling cannot reset the budget)."""

    spike_factor: float = 10.0
    warmup_steps: int = 10
    ema_decay: float = 0.95
    budget: Optional[int] = 25


def init_anomaly_guard_state(values=None):
    """Replicated guard-state tree carried inside ``TrainState.guard`` —
    zeros for a fresh run, or the checkpointed carry (``values`` keyed like
    the tree) on resume. The one owner of the tree's structure/sharding:
    loop.py's resume path must build the exact layout the jitted step was
    traced with."""
    mesh = mesh_lib.get_mesh()
    repl = NamedSharding(mesh, P())
    values = values or {}

    def leaf(name, dtype):
        return jax.device_put(jnp.asarray(values.get(name, 0), dtype), repl)

    return {
        "gnorm_ema": leaf("gnorm_ema", jnp.float32),
        "good_steps": leaf("good_steps", jnp.int32),
        "skips": leaf("skips", jnp.int32),
    }


@dataclasses.dataclass(frozen=True)
class TrainingConfig:
    """Typed replacement for the reference's normalized config dict
    (trainer/trainer.py:32-144)."""

    optimizer: OptimizerConfig = OptimizerConfig()
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    context_parallel_size: int = 1
    expert_parallel_size: int = 1


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    # anomaly-guard carry ({"gnorm_ema", "good_steps", "skips"} replicated
    # scalars) when build_train_step runs with an AnomalyGuardConfig; None
    # (an empty pytree) otherwise — existing constructors stay valid.
    guard: Any = None


def neuronx_distributed_tpu_config(
    tensor_parallel_size: int = 1,
    pipeline_parallel_size: int = 1,
    context_parallel_size: int = 1,
    expert_parallel_size: int = 1,
    optimizer: Optional[OptimizerConfig] = None,
) -> TrainingConfig:
    """Build config + initialize the mesh (reference
    ``neuronx_distributed_config`` also calls initialize_model_parallel)."""
    cfg = TrainingConfig(
        optimizer=optimizer or OptimizerConfig(),
        tensor_parallel_size=tensor_parallel_size,
        pipeline_parallel_size=pipeline_parallel_size,
        context_parallel_size=context_parallel_size,
        expert_parallel_size=expert_parallel_size,
    )
    if not mesh_lib.model_parallel_is_initialized():
        mesh_lib.initialize_model_parallel(
            tensor_model_parallel_size=tensor_parallel_size,
            pipeline_model_parallel_size=pipeline_parallel_size,
            context_parallel_size=context_parallel_size,
            expert_model_parallel_size=expert_parallel_size,
        )
    return cfg


def make_lr_schedule(cfg: OptimizerConfig):
    if cfg.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=max(cfg.warmup_steps, 1),
            decay_steps=cfg.total_steps,
            end_value=cfg.learning_rate * cfg.min_lr_ratio,
        )
    if cfg.warmup_steps > 0:
        return optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
    return cfg.learning_rate


def make_optimizer(cfg: OptimizerConfig) -> optax.GradientTransformation:
    """AdamW with fp32 master state; clipping is done in the train step so the
    pre-clip norm can be reported (reference NxDOptimizer.step order:
    trainer/optimizer.py:122)."""
    return optax.adamw(
        learning_rate=make_lr_schedule(cfg),
        b1=cfg.beta1,
        b2=cfg.beta2,
        eps=cfg.eps,
        weight_decay=cfg.weight_decay,
    )


def initialize_parallel_model(model, rng_key, *sample_args):
    """jit-init the model and place params with their metadata shardings
    (reference initialize_parallel_model:147 — the meta-device +
    sequential-move machinery is unnecessary: jit materializes each shard
    directly on its device)."""
    boxed = jax.jit(model.init)(rng_key, *sample_args)
    shardings = param_shardings(boxed)
    params = jax.device_put(meta.unbox(boxed), shardings)
    return params, shardings


def initialize_parallel_optimizer(
    optimizer: optax.GradientTransformation,
    params,
    params_shardings,
    zero1: bool = True,
):
    """Init optax state and place it zero-1-sharded over (dp, cp)
    (reference initialize_parallel_optimizer:237 + NeuronZero1Optimizer)."""
    mesh = mesh_lib.get_mesh()
    state_shapes = jax.eval_shape(optimizer.init, params)
    specs = jax.tree.map(lambda s: s.spec, params_shardings)
    state_shardings = zero1_shardings_for_opt_state(
        state_shapes, params, specs, mesh=mesh, enabled=zero1
    )
    opt_state = jax.jit(optimizer.init, out_shardings=state_shardings)(params)
    return opt_state, state_shardings


def shard_batch(batch: Mapping[str, jax.Array]):
    """Place a host batch: batch dim over dp, sequence dim over cp
    (reference: DP split is the dataloader's job; here it is a device_put)."""
    mesh = mesh_lib.get_mesh()

    def put(x):
        spec = [None] * x.ndim
        if x.ndim >= 1:
            spec[0] = mesh_lib.DATA_AXES
        if x.ndim >= 2:
            spec[1] = mesh_lib.CP_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(put, dict(batch))


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """Per-token position WITHIN its segment for contiguous-run segment
    layouts (packed windows): positions restart at 0 at every document
    boundary, matching the unpacked per-document baseline's RoPE."""
    s = segment_ids.shape[-1]
    idx = jnp.arange(s, dtype=jnp.int32)
    is_new = jnp.concatenate(
        [
            jnp.ones(segment_ids.shape[:-1] + (1,), bool),
            segment_ids[..., 1:] != segment_ids[..., :-1],
        ],
        axis=-1,
    )
    seg_start = jax.lax.cummax(jnp.where(is_new, idx, 0), axis=segment_ids.ndim - 1)
    return idx - seg_start


def _accepts_segment_ids(model) -> bool:
    import inspect

    try:
        return "segment_ids" in inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        return False


def default_loss_fn(model, params, batch):
    seg = batch.get("segment_ids")
    if seg is not None and not _accepts_segment_ids(model):
        # every causal family threads segment_ids (round 5); this branch now
        # covers only models without the plumbing (e.g. bidirectional
        # BERT/ViT heads, external modules): train concat-and-chunk style,
        # keeping the model-independent boundary-label loss_mask
        logger.warning(
            "%s takes no segment_ids — packed documents will attend across "
            "boundaries for this model (loss_mask still applies)",
            type(model).__name__,
        )
        seg = None
    if seg is not None:
        # packed documents: attention isolated per document (flash kernel
        # segment mask) and RoPE restarted per document
        logits = model.apply(
            params, batch["input_ids"],
            positions=segment_positions(seg), segment_ids=seg,
        )
    else:
        logits = model.apply(params, batch["input_ids"])
    if isinstance(logits, tuple):
        raise TypeError(
            f"{type(model).__name__}.apply returns (logits, aux) — MoE "
            "objectives need the aux losses: pass "
            "loss_fn=lambda p, b: model.loss(p, b['input_ids'], "
            "b['labels'], segment_ids=b.get('segment_ids'), "
            "loss_mask=b.get('loss_mask')) to build_train_step"
        )
    losses = parallel_cross_entropy(logits, batch["labels"])
    mask = batch.get("loss_mask")
    if mask is not None:
        return (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
    return losses.mean()


def build_train_step(
    model,
    optimizer: optax.GradientTransformation,
    params_shardings,
    opt_state_shardings,
    max_grad_norm: float = 1.0,
    loss_fn: Optional[Callable] = None,
    value_and_grad_fn: Optional[Callable] = None,
    grad_accum_steps: int = 1,
    anomaly_guard: Optional[AnomalyGuardConfig] = None,
):
    """One jitted SPMD train step: fwd → bwd → clip → update
    (reference: the whole NxDOptimizer.step pipeline, trainer/optimizer.py:122).
    State is donated; shardings are pinned so ZeRO-1 layout persists across
    steps instead of being renegotiated by the partitioner.

    With ``anomaly_guard`` set, the step additionally computes a
    ``good_step`` flag ON DEVICE (finite loss/grad-norm, no grad-norm spike
    vs the EMA carried in ``state.guard``) and selects the applied update
    per-leaf with it — an anomalous step leaves params and optimizer state
    bit-identical with NO host round-trip and NO recompile (one program
    serves clean and anomalous batches; the clean path's only cost is the
    selects). Metrics gain ``good_step`` and the cumulative device
    ``anomaly_skips`` counter the host budgets against.
    """
    from neuronx_distributed_tpu.optim.zero1 import (
        build_explicit_zero1_update,
        opt_state_is_zero1_sharded,
    )

    if value_and_grad_fn is None:
        loss_fn = loss_fn or partial(default_loss_fn, model)
        if grad_accum_steps > 1:
            # batch leaves arrive shaped (A, B/A, ...) (pipeline.model.
            # microbatch); grads accumulate inside one jitted scan — the
            # reference's host-side grad-accum loop, without the dispatches
            base_vag = jax.value_and_grad(loss_fn)

            def value_and_grad_fn(params, batch):
                def body(carry, mb):
                    loss_acc, g_acc = carry
                    loss, g = base_vag(params, mb)
                    g_acc = jax.tree.map(lambda a, b: a + b, g_acc, g)
                    return (loss_acc + loss.astype(jnp.float32), g_acc), None

                zeros = jax.tree.map(jnp.zeros_like, params)
                (loss_sum, g_sum), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zeros), batch
                )
                inv = 1.0 / grad_accum_steps
                return loss_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)
        else:
            value_and_grad_fn = jax.value_and_grad(loss_fn)
    mesh = mesh_lib.get_mesh()
    repl = NamedSharding(mesh, P())
    guard_shardings = (
        {"gnorm_ema": repl, "good_steps": repl, "skips": repl}
        if anomaly_guard is not None
        else None
    )
    state_shardings = TrainState(
        step=repl, params=params_shardings, opt_state=opt_state_shardings,
        guard=guard_shardings,
    )
    # Under pipeline parallelism the GSPMD zero-1 formulation crashes the XLA
    # partitioner (see build_explicit_zero1_update); route the update through
    # the explicit shard-step-allgather path instead.
    explicit_z1 = (
        mesh.shape.get(mesh_lib.PP_AXIS, 1) > 1
        and opt_state_is_zero1_sharded(opt_state_shardings)
    )
    z1_update = (
        build_explicit_zero1_update(optimizer, params_shardings, opt_state_shardings)
        if explicit_z1
        else None
    )

    def step_fn(state: TrainState, batch):
        loss, grads = value_and_grad_fn(state.params, batch)
        grads, grad_norm = clip_grad_norm(grads, max_grad_norm)
        if z1_update is not None:
            new_params, new_opt_state = z1_update(grads, state.opt_state, state.params)
        else:
            updates, new_opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "grad_norm": grad_norm}
        new_guard = state.guard
        if anomaly_guard is not None:
            g = state.guard
            finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
            warmed = g["good_steps"] >= anomaly_guard.warmup_steps
            # spike check on the PRE-clip norm (clipping would mask it)
            spike = warmed & (
                grad_norm > anomaly_guard.spike_factor * g["gnorm_ema"]
            )
            good = finite & ~spike
            # anomalous step: keep params AND opt state bit-identical —
            # the whole decision stays on device, no host sync, and one
            # program serves both outcomes (no recompile on anomaly)
            new_params = jax.tree.map(
                lambda n, o: jnp.where(good, n, o), new_params, state.params
            )
            new_opt_state = jax.tree.map(
                lambda n, o: jnp.where(good, n, o),
                new_opt_state, state.opt_state,
            )
            d = anomaly_guard.ema_decay
            ema = jnp.where(
                g["good_steps"] == 0,
                grad_norm,
                d * g["gnorm_ema"] + (1.0 - d) * grad_norm,
            )
            new_guard = {
                # the EMA learns only from good steps — a spike that got
                # skipped must not normalize the next one
                "gnorm_ema": jnp.where(good, ema, g["gnorm_ema"]),
                "good_steps": g["good_steps"] + good.astype(jnp.int32),
                "skips": g["skips"] + (1 - good.astype(jnp.int32)),
            }
            metrics["good_step"] = good
            metrics["anomaly_skips"] = new_guard["skips"]
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state,
            guard=new_guard,
        )
        return new_state, metrics

    return jax.jit(
        step_fn,
        donate_argnums=(0,),
        in_shardings=(state_shardings, None),
        out_shardings=(state_shardings, repl),
    )


def committed_step0() -> jax.Array:
    """The initial step scalar, COMMITTED-replicated like every later step's
    output — an uncommitted ``zeros()`` makes the second call's signature
    differ and silently recompiles the whole train step once."""
    return jax.device_put(
        jnp.zeros((), jnp.int32), NamedSharding(mesh_lib.get_mesh(), P())
    )


def create_train_state(model, optimizer, rng_key, *sample_args, zero1: bool = True):
    """Convenience: materialize params + opt state, return (state, train_step_builder_args)."""
    params, p_shardings = initialize_parallel_model(model, rng_key, *sample_args)
    opt_state, s_shardings = initialize_parallel_optimizer(
        optimizer, params, p_shardings, zero1=zero1
    )
    state = TrainState(step=committed_step0(), params=params, opt_state=opt_state)
    return state, p_shardings, s_shardings

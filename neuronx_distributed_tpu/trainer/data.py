"""Offline-tokenized corpus → packed fixed-length training batches
(reference: ``examples/training/llama/training_utils.py`` ``pack_dataset:33``
concat-and-chunk packing + ``create_llama_pretraining_dataset:104`` seeded
DistributedSampler/DataLoader).

The TPU-native formulation is single-controller: ONE iterator yields the
GLOBAL batch (the train step's ``shard_batch``/``prepare_batch`` places rows
across dp), so the per-rank DistributedSampler machinery disappears. What
stays, redesigned:

* **packing** — documents are concatenated (optionally separated by an EOS
  token) and chopped into ``seq_len + 1`` windows; window ``w`` yields
  ``input_ids = w[:-1]``, ``labels = w[1:]`` (the reference's
  concat-and-chunk with the remainder dropped at the corpus end);
* **deterministic shuffle** — window order is a seeded permutation,
  re-drawn per epoch from ``fold(seed, epoch)`` — resume-stable and
  dp-size-independent;
* **memory-mapped input** — ``.npy`` token streams load lazily; only the
  windows of the current batch are materialized.

Offline tokenization (this container has no network egress; on a dev host):

    from transformers import AutoTokenizer
    import numpy as np
    tok = AutoTokenizer.from_pretrained(...)
    ids = [tok(d)["input_ids"] for d in documents]
    np.savez("corpus.npz",
             tokens=np.concatenate(ids).astype(np.int32),
             offsets=np.cumsum([0] + [len(x) for x in ids]).astype(np.int64))

Accepted inputs: ``.npy`` 1-D token stream, ``.npy`` 2-D pre-packed
``(N, seq_len+1)`` windows, or ``.npz`` with ``tokens`` (+ optional
``offsets`` document boundaries, used to insert EOS separators).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

# --- exact-resume protocol ----------------------------------------------------
# A checkpointable data source exposes ``state() -> dict`` (a small
# JSON-able cursor) and ``restore(state)``. ``Trainer.fit`` persists the
# cursor into every checkpoint's user_content and restores it on
# ``resume_from=...`` — the load-bearing half of bit-identical resume
# (params/optimizer come back exactly via orbax; the batch STREAM must too).
# Both iterators here hold ONE cursor on the source object (the
# single-controller loop has one consumer); ``iter()`` continues from the
# cursor rather than restarting.


def pack_documents(
    docs, seq_len: int, eos_token_id: Optional[int] = None,
    return_segments: bool = False,
):
    """Concatenate ``docs`` (list of 1-D int arrays), optionally separated by
    ``eos_token_id``, and chop into ``(N, seq_len + 1)`` windows (the
    reference's chunk(); the tail remainder shorter than a window is
    dropped).

    With ``return_segments`` also returns a parallel ``(N, seq_len + 1)``
    int32 array of per-token document ids (the EOS separator belongs to the
    document it ends). Fed to the model as ``segment_ids``, these make packed
    training attend WITHIN documents only — the flash kernel's equal-segment
    block mask — instead of leaking across every document boundary."""
    parts, seg_parts = [], []
    for i, d in enumerate(docs):
        arr = np.asarray(d, np.int32).reshape(-1)
        n_tok = len(arr) + (1 if eos_token_id is not None else 0)
        parts.append(arr)
        if eos_token_id is not None:
            parts.append(np.asarray([eos_token_id], np.int32))
        seg_parts.append(np.full((n_tok,), i, np.int32))
    stream = np.concatenate(parts) if parts else np.zeros((0,), np.int32)
    segs = np.concatenate(seg_parts) if seg_parts else np.zeros((0,), np.int32)
    w = seq_len + 1
    n = len(stream) // w
    if n == 0:
        raise ValueError(
            f"corpus has {len(stream)} tokens — not enough for one "
            f"{w}-token window"
        )
    windows = stream[: n * w].reshape(n, w)
    if not return_segments:
        return windows
    return windows, segs[: n * w].reshape(n, w)


class PackedCorpus:
    """Iterable over packed ``{"input_ids", "labels"}`` batches with a
    deterministic per-epoch shuffle.

    ``path``: ``.npy`` / ``.npz`` per the module docstring. The iterator is
    infinite (epochs chain), matching ``Trainer.fit``'s data contract;
    ``num_batches_per_epoch`` tells the caller what one pass covers."""

    def __init__(
        self,
        path: str,
        seq_len: int,
        batch_size: int,
        seed: int = 0,
        shuffle: bool = True,
        eos_token_id: Optional[int] = None,
        emit_segments: bool = True,
    ) -> None:
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = shuffle
        w = self.seq_len + 1
        # per-window document ids, emitted as segment_ids + loss_mask when
        # document boundaries are known (npz offsets) — without them packed
        # windows attend across documents and train on boundary labels
        self.segments = None

        if path.endswith(".npz"):
            archive = np.load(path)
            if "tokens" in archive.files:
                tokens = archive["tokens"]
                if "offsets" in archive.files:
                    off = archive["offsets"]
                    docs = [tokens[off[i] : off[i + 1]] for i in range(len(off) - 1)]
                    if emit_segments:
                        self.windows, self.segments = pack_documents(
                            docs, seq_len, eos_token_id, return_segments=True
                        )
                    else:
                        self.windows = pack_documents(docs, seq_len, eos_token_id)
                else:
                    self.windows = pack_documents([tokens], seq_len, None)
            else:
                self.windows = pack_documents(
                    [archive[archive.files[0]].reshape(-1)], seq_len, None
                )
        else:
            arr = np.load(path, mmap_mode="r")
            if arr.ndim == 2:
                if arr.shape[1] != w:
                    raise ValueError(
                        f"pre-packed corpus windows are {arr.shape[1]} wide; "
                        f"need seq_len+1 = {w}"
                    )
                self.windows = arr  # stays memory-mapped
            else:
                n = arr.shape[0] // w
                if n == 0:
                    raise ValueError(
                        f"corpus has {arr.shape[0]} tokens — not enough for "
                        f"one {w}-token window"
                    )
                # a reshaped view of the memmap — windows stay lazy
                self.windows = arr[: n * w].reshape(n, w)

        if len(self.windows) < self.batch_size:
            raise ValueError(
                f"corpus has {len(self.windows)} windows < batch_size "
                f"{self.batch_size}"
            )
        self.num_batches_per_epoch = len(self.windows) // self.batch_size
        # exact-resume cursor (see the protocol note above): epoch + index
        # of the NEXT batch within it; the permutation is re-derivable from
        # (seed, epoch), so this tiny pair IS the full stream position
        self._epoch = 0
        self._cursor = 0
        self._order_cache: Optional[tuple] = None

    def state(self) -> dict:
        """JSON-able stream cursor (position of the NEXT batch)."""
        return {"epoch": int(self._epoch), "batch": int(self._cursor)}

    def restore(self, state: dict) -> None:
        """Reposition the stream; takes effect on the next ``next()`` even
        for iterators created before the restore."""
        self._epoch = int(state["epoch"])
        self._cursor = int(state["batch"])

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.windows))
        return np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch])
        ).permutation(len(self.windows))

    def _order_for(self, epoch: int) -> np.ndarray:
        if self._order_cache is None or self._order_cache[0] != epoch:
            self._order_cache = (epoch, self._epoch_order(epoch))
        return self._order_cache[1]

    def __iter__(self) -> Iterator[dict]:
        while True:
            if self._cursor >= self.num_batches_per_epoch:
                self._epoch += 1
                self._cursor = 0
            order = self._order_for(self._epoch)
            b = self._cursor
            self._cursor += 1
            idx = order[b * self.batch_size : (b + 1) * self.batch_size]
            # fancy-index materializes just this batch from the memmap;
            # sorted first (memmap reads in file order), then restored
            sort = np.argsort(idx)
            rows = np.asarray(self.windows[idx[sort]], np.int32)
            rows = rows[np.argsort(sort)]
            batch = {"input_ids": rows[:, :-1], "labels": rows[:, 1:]}
            if self.segments is not None:
                seg = np.asarray(self.segments[idx[sort]], np.int32)
                seg = seg[np.argsort(sort)]
                batch["segment_ids"] = seg[:, :-1]
                # a label drawn from the NEXT document (the token after a
                # boundary) is noise — mask it from the loss
                batch["loss_mask"] = (
                    seg[:, :-1] == seg[:, 1:]
                ).astype(np.float32)
            yield batch


class SyntheticTokens:
    """Seeded infinite random-token batches with the ``state()/restore()``
    exact-resume protocol (O(1) restore: batch ``i`` is drawn from
    ``SeedSequence([seed, i])``, so the cursor is just ``i``). The hermetic
    stand-in for a tokenized corpus in examples, bench children, and chaos
    tests.

    ``emit_mask`` attaches an all-ones ``loss_mask`` — numerically the
    plain mean loss, but its presence lets the chaos
    :class:`~neuronx_distributed_tpu.trainer.faults.FaultInjector` corrupt
    it without changing the batch pytree (no retrace on injection)."""

    def __init__(self, vocab_size: int, batch_size: int, seq_len: int,
                 seed: int = 0, emit_mask: bool = True):
        self.vocab_size = int(vocab_size)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.seed = int(seed)
        self.emit_mask = emit_mask
        self._i = 0

    def state(self) -> dict:
        return {"batch": int(self._i)}

    def restore(self, state: dict) -> None:
        self._i = int(state["batch"])

    def __iter__(self) -> Iterator[dict]:
        while True:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, self._i])
            )
            ids = rng.integers(
                0, self.vocab_size,
                (self.batch_size, self.seq_len + 1), dtype=np.int32,
            )
            self._i += 1
            batch = {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}
            if self.emit_mask:
                batch["loss_mask"] = np.ones(
                    (self.batch_size, self.seq_len), np.float32
                )
            yield batch

"""Checkpoint save/load with the reference's tag/done/retention protocol.

Reference analogue: ``trainer/checkpoint.py`` (``save_checkpoint:653``,
``load_checkpoint:837``) and ``trainer/checkpoint_storage.py``. The reference's
machinery — per-tag directories with ``done`` markers and a ``newest`` pointer,
retention of ``num_kept_ckpts`` with corrupted-tag cleanup
(``_determine_remove_tags:65``), async saves through a single-worker executor
with an atexit flush (``CheckpointIOState``, ``:109-323``), xser
tensor-per-file writes load-balanced by Karmarkar-Karp bin packing
(``_xser_save_data:476``) and broadcast-based replicated loads
(``_xser_load_data:346``) — maps onto TPU/JAX as:

* tensor IO: orbax/tensorstore sharded array writes. Each host writes exactly
  its addressable shards, which subsumes the reference's bin-packing
  load-balancing; replicated loads are single-read + XLA broadcast, which
  subsumes the all-reduce-as-broadcast trick.
* resharding across (tp, pp, dp, ep) layout changes: restore against a target
  tree of ``jax.ShapeDtypeStruct`` carrying the *new* ``NamedSharding`` —
  tensorstore reads each device's slice directly, replacing the reference's
  offline ``convert_zero_checkpoints`` DP reshard for the common cases.
* the tag/done/newest/retention control protocol is kept as-is (pure
  filesystem metadata, deliberately identical semantics).

Master-weight dedup (``avoid_saving_lower_precision_weights``,
checkpoint.py:643,761): when the optimizer state carries fp32 master copies,
pass ``items={"optimizer": ...}`` only and rebuild bf16 params on load — here
that is a user-level choice, not a flag, because params/opt-state are explicit
pytrees rather than module attributes.
"""

from __future__ import annotations

import atexit
import json
import os
import shutil
import threading
from typing import Any, Dict, List, Mapping, Optional, Tuple

import jax

from neuronx_distributed_tpu.utils.logger import get_logger
from neuronx_distributed_tpu.utils.retry import RetryPolicy, with_retries

logger = get_logger(__name__)

DONE_MARKER = "done"
NEWEST_FILE = "newest"
META_FILE = "user_content.json"
_ITEMS_DIRNAME = "state"


# --- storage abstraction ------------------------------------------------------
# Reference: BaseCheckpointStorage (checkpoint_storage.py:46) with local-FS
# (:138) and S3 (:236) implementations. Tensor IO goes through orbax/
# tensorstore; this abstraction covers the control-plane metadata only.


class BaseCheckpointStorage:
    def __init__(self, dirname: str):
        self._dirname = dirname

    @property
    def dirname(self) -> str:
        return self._dirname

    def file_exists(self, filename: str) -> bool:
        raise NotImplementedError

    def file_mtime(self, filename: str) -> float:
        raise NotImplementedError

    def remove_file(self, filename: str) -> None:
        raise NotImplementedError

    def save_text(self, text: str, filename: str) -> None:
        raise NotImplementedError

    def load_text(self, filename: str) -> str:
        raise NotImplementedError

    # --- byte-level access (integrity manifests, ISSUE 20) --------------------

    def list_files(self, prefix: str) -> List[str]:
        """Relative paths (to ``prefix``) of every FILE under it,
        recursive, sorted — the digest surface of a checkpoint tag."""
        raise NotImplementedError

    def save_bytes(self, data: bytes, filename: str) -> None:
        raise NotImplementedError

    def load_bytes(self, filename: str) -> bytes:
        raise NotImplementedError

    def list_checkpoint_tags(self) -> List[str]:
        raise NotImplementedError

    def remove_checkpoint(self, tag: str) -> None:
        raise NotImplementedError

    # --- tensor-plane path handling (orbax/tensorstore target) ---------------

    def items_url(self, tag: str) -> str:
        """Path/URI handed to orbax for the tensor payload of ``tag``."""
        raise NotImplementedError

    def prepare_tag(self, tag: str) -> None:
        """Make the tag container writable and clear stale tensor payloads."""
        raise NotImplementedError

    def list_items(self, tag: str) -> List[str]:
        """Names of saved item subtrees under ``tag`` (model, optimizer, ...)."""
        raise NotImplementedError


class FilesystemCheckpointStorage(BaseCheckpointStorage):
    """Local/NFS directory storage (reference checkpoint_storage.py:138)."""

    def file_exists(self, filename: str) -> bool:
        return os.path.exists(os.path.join(self._dirname, filename))

    def file_mtime(self, filename: str) -> float:
        return os.path.getmtime(os.path.join(self._dirname, filename))

    def remove_file(self, filename: str) -> None:
        path = os.path.join(self._dirname, filename)
        if os.path.exists(path):
            os.remove(path)

    def save_text(self, text: str, filename: str) -> None:
        path = os.path.join(self._dirname, filename)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)

    def load_text(self, filename: str) -> str:
        with open(os.path.join(self._dirname, filename)) as f:
            return f.read()

    def list_files(self, prefix: str) -> List[str]:
        root = os.path.join(self._dirname, prefix)
        if not os.path.isdir(root):
            return []
        out = []
        for dirpath, _, names in os.walk(root):
            for name in names:
                out.append(
                    os.path.relpath(os.path.join(dirpath, name), root)
                )
        return sorted(out)

    def save_bytes(self, data: bytes, filename: str) -> None:
        path = os.path.join(self._dirname, filename)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def load_bytes(self, filename: str) -> bytes:
        with open(os.path.join(self._dirname, filename), "rb") as f:
            return f.read()

    def list_checkpoint_tags(self) -> List[str]:
        if not os.path.isdir(self._dirname):
            return []
        return sorted(
            d
            for d in os.listdir(self._dirname)
            if os.path.isdir(os.path.join(self._dirname, d))
        )

    def remove_checkpoint(self, tag: str) -> None:
        shutil.rmtree(os.path.join(self._dirname, tag), ignore_errors=True)

    def items_url(self, tag: str) -> str:
        return os.path.abspath(os.path.join(self._dirname, tag, _ITEMS_DIRNAME))

    def prepare_tag(self, tag: str) -> None:
        tag_dir = os.path.join(self._dirname, tag)
        os.makedirs(tag_dir, exist_ok=True)
        items = os.path.join(tag_dir, _ITEMS_DIRNAME)
        if os.path.exists(items):
            shutil.rmtree(items)

    def list_items(self, tag: str) -> List[str]:
        target = self.items_url(tag)
        return [
            d for d in os.listdir(target) if os.path.isdir(os.path.join(target, d))
        ]


# Transient error classes worth retrying on object stores (reference:
# tenacity retry on ClientError/slow-down, checkpoint_storage.py:236-330).
_TRANSIENT_ERRORS: Tuple[type, ...] = (OSError, IOError, TimeoutError)


def _with_retries(fn, what: str, max_attempts: int = 5,
                  first_wait: float = 4.0, min_wait: float = 0.5,
                  sleep=None, rng=None):
    """Reference ``wait_decrementing_with_jitter`` (checkpoint_storage.py:236):
    retry on transient object-store errors with a DEcreasing jittered wait —
    the first wait is longest (ride out a throttle burst), later waits shrink.

    The schedule lives in :mod:`neuronx_distributed_tpu.utils.retry`
    (shared with the serving engine's dispatch-recovery loop); this wrapper
    keeps the checkpoint call sites' signature and behavior bit-identical
    to the pre-extraction implementation (seeded-RNG pinned in
    ``tests/utils/test_retry.py``)."""
    return with_retries(
        fn, what,
        policy=RetryPolicy(
            max_attempts=max_attempts, first_wait=first_wait,
            min_wait=min_wait,
        ),
        transient=_TRANSIENT_ERRORS,
        sleep=sleep, rng=rng,
    )


class FsspecCheckpointStorage(BaseCheckpointStorage):
    """Object-store / URI storage over fsspec (reference: the S3 storage with
    CRT + tenacity retries, checkpoint_storage.py:236-330). Handles any
    registered protocol — ``gs://`` (gcsfs), ``s3://`` (s3fs), ``file://``,
    ``memory://`` — with the same tag/done/newest/retention semantics as the
    filesystem backend and decrementing-jitter retries on transient errors.

    The tensor payload goes to the SAME URI through orbax/tensorstore, which
    speak ``gs://`` natively on TPU VMs; ``file://`` URIs are translated to
    plain paths for orbax (tensorstore treats bare paths as local files).
    """

    def __init__(self, url: str):
        super().__init__(url.rstrip("/"))
        self._lazy_fs = None  # built on first IO; see _fs
        self._protocol = self._dirname.split("://", 1)[0]

    @property
    def _fs(self):
        # Filesystem construction can open network connections and run
        # credential discovery (gcsfs probes the GCE metadata server, with
        # multi-second timeouts off-GCP) — URI dispatch and storage
        # selection must stay pure, so defer until the first real IO.
        if self._lazy_fs is None:
            import fsspec

            self._lazy_fs, self._lazy_root = fsspec.core.url_to_fs(
                self._dirname
            )
        return self._lazy_fs

    @_fs.setter
    def _fs(self, fs):
        # fault-injection harnesses wrap the live filesystem in place
        self._fs  # materialize so _lazy_root is set
        self._lazy_fs = fs

    @property
    def _root(self):
        self._fs
        return self._lazy_root

    def _path(self, filename: str) -> str:
        return f"{self._root}/{filename}"

    def file_exists(self, filename: str) -> bool:
        return _with_retries(
            lambda: self._fs.exists(self._path(filename)),
            f"exists({filename})",
        )

    def file_mtime(self, filename: str) -> float:
        def get():
            info = self._fs.info(self._path(filename))
            m = info.get("mtime") or info.get("LastModified") or 0.0
            return m.timestamp() if hasattr(m, "timestamp") else float(m)

        return _with_retries(get, f"mtime({filename})")

    def remove_file(self, filename: str) -> None:
        def rm():
            p = self._path(filename)
            if self._fs.exists(p):
                self._fs.rm_file(p)

        _with_retries(rm, f"remove({filename})")

    def save_text(self, text: str, filename: str) -> None:
        def put():
            with self._fs.open(self._path(filename), "w") as f:
                f.write(text)

        _with_retries(put, f"save_text({filename})")

    def load_text(self, filename: str) -> str:
        def get():
            with self._fs.open(self._path(filename), "r") as f:
                return f.read()

        return _with_retries(get, f"load_text({filename})")

    def list_files(self, prefix: str) -> List[str]:
        def ls():
            root = self._path(prefix)
            if not self._fs.exists(root):
                return []
            out = []
            for p in self._fs.find(root):
                rel = p[len(root):].lstrip("/")
                if rel:
                    out.append(rel)
            return sorted(out)

        return _with_retries(ls, f"list_files({prefix})")

    def save_bytes(self, data: bytes, filename: str) -> None:
        def put():
            with self._fs.open(self._path(filename), "wb") as f:
                f.write(data)

        _with_retries(put, f"save_bytes({filename})")

    def load_bytes(self, filename: str) -> bytes:
        def get():
            with self._fs.open(self._path(filename), "rb") as f:
                return f.read()

        return _with_retries(get, f"load_bytes({filename})")

    def list_checkpoint_tags(self) -> List[str]:
        def ls():
            if not self._fs.exists(self._root):
                return []
            out = []
            for info in self._fs.ls(self._root, detail=True):
                if info.get("type") == "directory":
                    out.append(info["name"].rstrip("/").rsplit("/", 1)[-1])
            return sorted(out)

        return _with_retries(ls, "list_tags")

    def remove_checkpoint(self, tag: str) -> None:
        def rm():
            p = self._path(tag)
            if self._fs.exists(p):
                self._fs.rm(p, recursive=True)

        _with_retries(rm, f"remove_checkpoint({tag})")

    def items_url(self, tag: str) -> str:
        url = f"{self._dirname}/{tag}/{_ITEMS_DIRNAME}"
        if self._protocol == "file":
            # orbax/tensorstore want a plain path for local files
            return url[len("file://"):]
        return url

    def prepare_tag(self, tag: str) -> None:
        def prep():
            items = self._path(f"{tag}/{_ITEMS_DIRNAME}")
            if self._fs.exists(items):
                self._fs.rm(items, recursive=True)
            # object stores have no real directories; makedirs where supported
            try:
                self._fs.makedirs(self._path(tag), exist_ok=True)
            except Exception:
                pass

        _with_retries(prep, f"prepare_tag({tag})")

    def list_items(self, tag: str) -> List[str]:
        def ls():
            target = self._path(f"{tag}/{_ITEMS_DIRNAME}")
            return sorted(
                info["name"].rstrip("/").rsplit("/", 1)[-1]
                for info in self._fs.ls(target, detail=True)
                if info.get("type") == "directory"
            )

        return _with_retries(ls, f"list_items({tag})")


def create_checkpoint_storage(dirname: str) -> BaseCheckpointStorage:
    """Reference: create_checkpoint_storage (checkpoint_storage.py:46) — local
    paths get the filesystem backend; any ``scheme://`` URI (``gs://``,
    ``s3://``, ``file://``, ``memory://``) goes through fsspec with
    retry/backoff. TPU pods checkpoint to GCS: pass ``gs://bucket/run1``."""
    if "://" in dirname:
        return FsspecCheckpointStorage(dirname)
    return FilesystemCheckpointStorage(dirname)


# --- async IO state -----------------------------------------------------------


class CheckpointIOState:
    """Tracks in-flight async saves (reference CheckpointIOState,
    trainer/checkpoint.py:109-323). Commits run on a single-worker executor —
    like the reference's single-worker ThreadPoolExecutor — so done-marker /
    ``newest`` / retention updates happen strictly in submission order even
    when an earlier save's tensorstore flush outlives a later one's.
    In-flight tags are exposed so retention never deletes a save that simply
    has not committed yet."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._executor: Optional[Any] = None
        self._pending: List[Any] = []  # futures
        self._in_flight: List[str] = []

    def in_flight_tags(self) -> List[str]:
        with self._lock:
            return list(self._in_flight)

    def register(self, tag: str) -> None:
        """Mark ``tag`` as being written BEFORE any bytes land on disk, so a
        concurrent retention pass never classifies it as corrupted."""
        with self._lock:
            if tag not in self._in_flight:
                self._in_flight.append(tag)

    def unregister(self, tag: str) -> None:
        with self._lock:
            if tag in self._in_flight:
                self._in_flight.remove(tag)

    def begin(
        self,
        checkpointer: Any,
        storage: BaseCheckpointStorage,
        tag: str,
        num_kept_ckpts: Optional[int],
    ) -> None:
        """``tag`` must already be :meth:`register`-ed."""
        import concurrent.futures

        def _finish() -> None:
            from neuronx_distributed_tpu.integrity.checkpoint import (
                write_manifest,
            )

            try:
                checkpointer.wait_until_finished()
                # digest what tensorstore just flushed BEFORE the done
                # marker blesses it (ISSUE 20 verified checkpoints)
                write_manifest(storage, tag)
                _commit(storage, tag, num_kept_ckpts, current_tag=tag)
                logger.info("async checkpoint '%s' committed", tag)
            finally:
                self.unregister(tag)
                checkpointer.close()

        with self._lock:
            if self._executor is None:
                self._executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt-commit"
                )
            # Surface failures of already-finished commits instead of silently
            # dropping them (a failed save must not go unnoticed).
            still_pending = []
            for f in self._pending:
                if f.done():
                    f.result()  # raises if the commit failed
                else:
                    still_pending.append(f)
            self._pending = still_pending
            self._pending.append(self._executor.submit(_finish))

    def wait_all(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for f in pending:
            f.result()


_IO_STATE = CheckpointIOState()
atexit.register(_IO_STATE.wait_all)


def finalize_checkpoints() -> None:
    """Block until every async save has committed (reference: atexit hook
    trainer/checkpoint.py:733)."""
    _IO_STATE.wait_all()


# --- save/load ----------------------------------------------------------------


def _orbax():
    import orbax.checkpoint as ocp

    return ocp


def _commit(
    storage: BaseCheckpointStorage,
    tag: str,
    num_kept_ckpts: Optional[int],
    current_tag: Optional[str] = None,
) -> None:
    storage.save_text("", os.path.join(tag, DONE_MARKER))
    storage.save_text(tag, NEWEST_FILE)
    if num_kept_ckpts is not None and num_kept_ckpts > 0:
        victims = _determine_remove_tags(storage, num_kept_ckpts, current_tag)
        for victim in victims:
            logger.info("retention: removing checkpoint '%s'", victim)
            storage.remove_checkpoint(victim)


def _tag_step(tag: str) -> int:
    """Trailing integer of a tag when present (step_100 → 100), else -1."""
    digits = ""
    for ch in reversed(tag):
        if ch.isdigit():
            digits = ch + digits
        else:
            break
    return int(digits) if digits else -1


def _tag_order_key(storage: BaseCheckpointStorage, tag: str):
    """Retention/newest ordering: done-marker mtime (save-completion order),
    with the trailing step number as tie-break. mtime makes non-numeric tags
    (e.g. 'hf_import') order by recency instead of being evicted first."""
    try:
        mtime = storage.file_mtime(os.path.join(tag, DONE_MARKER))
    except OSError:
        mtime = 0.0
    return (mtime, _tag_step(tag))


def _determine_remove_tags(
    storage: BaseCheckpointStorage,
    num_kept_ckpts: int,
    current_tag: Optional[str] = None,
) -> List[str]:
    """Reference _determine_remove_tags (trainer/checkpoint.py:65-97): tags
    without a ``done`` marker are corrupted leftovers and removed outright —
    EXCEPT tags whose save is still in flight (they have no marker yet by
    construction). ``current_tag`` — the tag whose commit is running this
    retention pass — is still registered in-flight but already has its done
    marker, so it counts as completed (otherwise async retention would keep
    one extra checkpoint). Completed tags beyond the newest ``num_kept_ckpts``
    are removed oldest-first."""
    in_flight = set(_IO_STATE.in_flight_tags()) - {current_tag}
    tags = [t for t in storage.list_checkpoint_tags() if t not in in_flight]
    done = [t for t in tags if storage.file_exists(os.path.join(t, DONE_MARKER))]
    corrupted = [t for t in tags if t not in done]
    done.sort(key=lambda t: _tag_order_key(storage, t))
    excess = done[: max(0, len(done) - num_kept_ckpts)]
    return corrupted + excess


def save_checkpoint(
    checkpoint_dir: str,
    tag: str,
    items: Mapping[str, Any],
    user_content: Optional[Dict[str, Any]] = None,
    num_kept_ckpts: Optional[int] = None,
    async_save: bool = False,
    storage: Optional[BaseCheckpointStorage] = None,
) -> None:
    """Save ``items`` (a dict of named pytrees, e.g. ``{"model": params,
    "optimizer": opt_state}``) under ``checkpoint_dir/tag``.

    Reference: save_checkpoint (trainer/checkpoint.py:653). Each item gets its
    own subtree so model-only loads never touch optimizer bytes, matching the
    reference's separate model/optimizer dirs.
    """
    ocp = _orbax()
    storage = storage or create_checkpoint_storage(checkpoint_dir)
    # Register before the tag dir exists so concurrent retention passes never
    # see a half-written save as a corrupted tag.
    _IO_STATE.register(tag)
    try:
        # Re-saving an existing tag: drop the stale done marker FIRST so a
        # crash mid-rewrite can never leave a half-written checkpoint that
        # still passes the done check.
        storage.remove_file(os.path.join(tag, DONE_MARKER))
        storage.prepare_tag(tag)
        if user_content is not None:
            storage.save_text(json.dumps(user_content), os.path.join(tag, META_FILE))

        target = storage.items_url(tag)
        # One Composite save → one tensorstore transaction for all items.
        args = ocp.args.Composite(
            **{k: ocp.args.StandardSave(v) for k, v in items.items()}
        )
        if async_save:
            checkpointer = ocp.AsyncCheckpointer(ocp.CompositeCheckpointHandler())
            try:
                checkpointer.save(target, args=args)
                _IO_STATE.begin(checkpointer, storage, tag, num_kept_ckpts)
            except Exception:
                _IO_STATE.unregister(tag)
                checkpointer.close()
                raise
            return  # _finish unregisters after commit
        with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as checkpointer:
            checkpointer.save(target, args=args)
        from neuronx_distributed_tpu.integrity.checkpoint import write_manifest

        # digest the flushed payload BEFORE the done marker blesses it
        # (ISSUE 20 verified checkpoints)
        write_manifest(storage, tag)
        _commit(storage, tag, num_kept_ckpts, current_tag=tag)
    finally:
        if not async_save:
            _IO_STATE.unregister(tag)


def latest_checkpoint_tag(checkpoint_dir: str) -> Optional[str]:
    """Resolve the newest completed tag: the ``newest`` pointer when valid,
    else the newest tag carrying a ``done`` marker.

    A ``newest`` pointer whose tag is MISSING its done marker means the
    run was killed between the tensor flush and the marker write — the
    pointed-at bytes are untrustworthy. The resolution falls back to the
    newest *done* tag and cleans up the corrupt leftover (the reference's
    ``_determine_remove_tags`` behavior), repointing ``newest`` at the
    fallback so later loads don't re-walk the corruption. Tags whose save
    is still in flight (async save registered, marker legitimately not yet
    written) are never touched.

    The in-flight guard is PROCESS-local (like the async-save machinery
    itself): a checkpoint directory has exactly one writer process.
    Calling this from a *different* process while that writer is
    mid-async-save can misread its not-yet-marked tag as a killed-mid-save
    leftover and delete it — inspect such directories read-only
    (``list_checkpoint_tags`` + done-marker checks) instead."""
    storage = create_checkpoint_storage(checkpoint_dir)
    corrupt_newest = None
    if storage.file_exists(NEWEST_FILE):
        tag = storage.load_text(NEWEST_FILE).strip()
        if storage.file_exists(os.path.join(tag, DONE_MARKER)):
            return tag
        if tag not in _IO_STATE.in_flight_tags():
            corrupt_newest = tag
    done = [
        t
        for t in storage.list_checkpoint_tags()
        if storage.file_exists(os.path.join(t, DONE_MARKER))
    ]
    newest_done = (
        max(done, key=lambda t: _tag_order_key(storage, t)) if done else None
    )
    if corrupt_newest is not None:
        logger.warning(
            "newest pointer targets '%s' which has no done marker (killed "
            "mid-save?) — removing it and falling back to the newest "
            "completed tag", corrupt_newest,
        )
        storage.remove_checkpoint(corrupt_newest)
        if newest_done is not None:
            storage.save_text(newest_done, NEWEST_FILE)
        else:
            storage.remove_file(NEWEST_FILE)
    return newest_done


def load_checkpoint(
    checkpoint_dir: str,
    tag: Optional[str] = None,
    items_target: Optional[Mapping[str, Any]] = None,
    verify_integrity: bool = True,
    on_corrupt: Optional[Any] = None,
) -> Tuple[Dict[str, Any], Optional[Dict[str, Any]], str]:
    """Load ``(items, user_content, tag)`` from ``checkpoint_dir``.

    ``tag=None`` resolves the newest completed checkpoint (reference
    load_checkpoint trainer/checkpoint.py:837). ``items_target`` maps item
    names to pytrees of arrays or ``jax.ShapeDtypeStruct`` with shardings —
    supplying shardings from a *different* mesh layout reshards on read
    (replacing the reference's offline zero-1/TP reshard converters for
    on-line cases). Omitted items are restored as host numpy arrays.

    With ``verify_integrity`` (the default), the tag's ``integrity.json``
    manifest is re-digested BEFORE orbax reads a byte: a mismatch (bytes
    rotted after a successful commit — the corruption the done-marker
    protocol cannot see) quarantines the tag by stripping its done marker,
    calls ``on_corrupt(tag, detail)`` when given, and falls back to the
    previous completed tag via ``latest_checkpoint_tag``'s corrupt-tag
    cleanup. Runs out of good tags → ``FileNotFoundError``. Pre-manifest
    checkpoints verify as legacy and load as before.
    """
    ocp = _orbax()
    storage = create_checkpoint_storage(checkpoint_dir)
    if tag is None:
        tag = latest_checkpoint_tag(checkpoint_dir)
        if tag is None:
            raise FileNotFoundError(f"no completed checkpoint under {checkpoint_dir}")
    if not storage.file_exists(os.path.join(tag, DONE_MARKER)):
        raise FileNotFoundError(f"checkpoint '{tag}' has no done marker (corrupted?)")
    if verify_integrity:
        from neuronx_distributed_tpu.integrity.checkpoint import verify_manifest

        while True:
            ok, detail = verify_manifest(storage, tag)
            if ok:
                if detail == "legacy":
                    logger.info(
                        "checkpoint '%s' predates integrity manifests — "
                        "loading unverified", tag,
                    )
                break
            logger.error(
                "checkpoint '%s' FAILED integrity verification (%s) — "
                "quarantining it and falling back to the previous "
                "completed tag", tag, detail,
            )
            if on_corrupt is not None:
                on_corrupt(tag, detail)
            # stripping the done marker makes the tag invisible to
            # latest_checkpoint_tag, whose corrupt-newest cleanup then
            # removes it and repoints `newest` at the fallback
            storage.remove_file(os.path.join(tag, DONE_MARKER))
            tag = latest_checkpoint_tag(checkpoint_dir)
            if tag is None:
                raise FileNotFoundError(
                    f"no completed checkpoint under {checkpoint_dir} "
                    "passes integrity verification"
                )

    target = storage.items_url(tag)
    item_names = (
        list(items_target.keys())
        if items_target is not None
        else storage.list_items(tag)
    )

    def _restore_arg(name: str):
        if items_target is not None and items_target.get(name) is not None:
            tmpl = items_target[name]
            abstract = jax.tree.map(
                lambda x: x
                if isinstance(x, jax.ShapeDtypeStruct)
                else jax.ShapeDtypeStruct(
                    jax.numpy.shape(x),
                    x.dtype,
                    sharding=getattr(x, "sharding", None),
                ),
                tmpl,
            )
            return ocp.args.StandardRestore(abstract)
        return ocp.args.StandardRestore()

    with ocp.Checkpointer(ocp.CompositeCheckpointHandler()) as checkpointer:
        restored = checkpointer.restore(
            target, args=ocp.args.Composite(**{n: _restore_arg(n) for n in item_names})
        )
    items = {n: restored[n] for n in item_names}

    user_content = None
    if storage.file_exists(os.path.join(tag, META_FILE)):
        user_content = json.loads(storage.load_text(os.path.join(tag, META_FILE)))
    return items, user_content, tag

"""High-level training loop with callbacks (reference:
``src/neuronx_distributed/lightning/`` — ``NeuronXLAStrategy``,
``NeuronLTModule``, the rank-0 TensorBoard logger with step gating
(logger.py:24), TQDM bar, and ``NeuronHooksCallback``; plus the examples'
``Throughput`` moving-average meter, training_utils.py:338).

Lightning's role in the reference — wiring parallel init, precision, the
train loop, logging, and checkpoint IO — collapses here into one plain
``Trainer`` class over the jitted train step. Callbacks get the same hook
points the reference's Lightning plugins use.

Fault tolerance — the unattended-safety contract the serving engine carries
(``serving/engine.py``), training-side (chaos-tested in
``tests/trainer/test_faults.py``):

* **Anomaly guards** — a ``good_step`` flag computed INSIDE the jitted step
  (``build_train_step(anomaly_guard=...)``): non-finite loss/grad-norm or a
  grad-norm spike vs the device-carried EMA skips the update on device with
  params/opt-state bit-identical, no host round-trip, no recompile. The
  host budgets cumulative skips against ``AnomalyGuardConfig.budget`` by
  reading the previous step's tiny flag pair AFTER the next step has been
  dispatched (the readback overlaps device compute — the clean path never
  stalls on the guard); exceeding the budget HALTS with an emergency
  checkpoint instead of silently training on garbage.
* **Dispatch recovery** — a failed train-step dispatch retries against the
  last known-good ``(state, step)`` snapshot (the pre-dispatch state:
  host-side failures leave donated buffers unconsumed) with the shared
  decrementing-jitter :class:`~neuronx_distributed_tpu.utils.retry.
  RetryPolicy`; ``max_attempts`` CONSECUTIVE failures — or a failure that
  consumed the donated buffers — land in HALTED with an emergency
  checkpoint of the surviving state (mirroring serving's
  ``dispatch_retry`` semantics).
* **Exact resume** — checkpoints carry the base RNG key, the data
  iterator's cursor (``state()/restore()`` protocol, trainer/data.py), and
  the throughput/step bookkeeping, so ``fit(resume_from=...)`` after a
  mid-run kill reproduces the uninterrupted run's loss curve
  bit-identically.
* **Graceful preemption** — SIGTERM/SIGINT finish the in-flight step,
  write a final ``step_N`` checkpoint through the done-marker protocol,
  and return cleanly (``trainer.preempted``); a second signal falls
  through to the original handler.
* **Callback isolation** — one callback raising in a hook is logged with
  its class name and counted (``callback_errors``), never fatal;
  ``on_train_end`` still runs for every callback.
* **Health** — ``trainer.health()`` reports ``OK/DEGRADED/HALTED``
  (DEGRADED = anomaly skip or dispatch retry within
  ``degraded_cooldown_steps``); counters mirror into the per-step metrics
  dict and Timeline instants.

Every fault path is drivable deterministically through
:class:`~neuronx_distributed_tpu.trainer.faults.FaultInjector`; with no
injector the hooks are no-ops."""

from __future__ import annotations

import dataclasses
import enum
import os
import signal as _signal
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_tpu.trainer.checkpoint import save_checkpoint
from neuronx_distributed_tpu.trainer.trainer import (
    AnomalyGuardConfig,
    OptimizerConfig,
    build_train_step,
    create_train_state,
    init_anomaly_guard_state,
    make_optimizer,
    shard_batch,
)
from neuronx_distributed_tpu.utils.logger import get_logger
from neuronx_distributed_tpu.utils.retry import RetryPolicy
from neuronx_distributed_tpu.utils.timeline import Timeline

logger = get_logger(__name__)


class TrainerHealth(enum.Enum):
    """Trainer health snapshot (``Trainer.health()``) — the serving
    engine's surface, training-side. ``OK`` — training normally.
    ``DEGRADED`` — progressing, but an anomaly skip or dispatch retry
    happened within the last ``degraded_cooldown_steps`` steps. ``HALTED``
    — the anomaly budget or dispatch retry budget is exhausted; the run
    stopped with an emergency checkpoint and ``halt_reason`` says why."""

    OK = "ok"
    DEGRADED = "degraded"
    HALTED = "halted"


class TrainerHalted(RuntimeError):
    """Raised by ``fit`` when the run halts (anomaly budget exceeded or
    dispatch retries exhausted). ``emergency_tag`` names the emergency
    checkpoint written before halting (``None`` if no checkpoint directory
    was known or the state was unusable)."""

    def __init__(self, reason: str, emergency_tag: Optional[str] = None):
        super().__init__(reason)
        self.reason = reason
        self.emergency_tag = emergency_tag


class Callback:
    """Hook points (reference: Lightning callback surface used by NxD)."""

    def on_train_start(self, trainer: "Trainer") -> None: ...

    def on_step_end(self, trainer: "Trainer", metrics: dict) -> None: ...

    def on_train_end(self, trainer: "Trainer") -> None: ...


class ThroughputMeter:
    """Moving-average seqs/s over the last N steps (reference Throughput,
    examples/training/llama/training_utils.py:338)."""

    def __init__(self, batch_size: int, window: int = 10):
        self.batch_size = batch_size
        self.window = window
        self._times: deque = deque(maxlen=window + 1)
        self.throughput = 0.0

    def update(self) -> float:
        self._times.append(time.perf_counter())
        if len(self._times) >= 2:
            dt = self._times[-1] - self._times[0]
            steps = len(self._times) - 1
            self.throughput = self.batch_size * steps / max(dt, 1e-9)
        return self.throughput


class MetricsLogger(Callback):
    """Rank-0 step-gated metric logging, optionally into TensorBoard
    (reference lightning/logger.py:24 NeuronTensorBoardLogger). Robustness
    counters (``anomaly_skips``/``dispatch_retries``/
    ``emergency_checkpoints``/``callback_errors``) ride the same metrics
    dict, so a fault-injected run's log explains itself."""

    def __init__(self, log_every: int = 10, tensorboard_dir: Optional[str] = None):
        self.log_every = log_every
        self._tb = None
        if tensorboard_dir is not None:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # tensorboard is optional
                logger.warning("tensorboard unavailable (%s); file logging only", e)

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.log_every != 0:
            return
        scalars = {k: float(v) for k, v in metrics.items()}
        logger.info(
            "step %d: %s", trainer.step,
            " ".join(f"{k}={v:.4f}" for k, v in scalars.items()),
        )
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, trainer.step)

    def on_train_end(self, trainer):
        if self._tb is not None:
            self._tb.flush()


class ProgressBar(Callback):
    """TQDM-style progress (reference: the Lightning TQDM bar plugin). Falls
    back to plain line logging when tqdm is unavailable."""

    def __init__(self, total_steps: Optional[int] = None):
        self.total_steps = total_steps
        self._bar = None

    def on_train_start(self, trainer):
        try:
            from tqdm import tqdm

            self._bar = tqdm(total=self.total_steps, desc="train", unit="step")
        except Exception:
            self._bar = None

    def on_step_end(self, trainer, metrics):
        if self._bar is not None:
            self._bar.update(1)
            self._bar.set_postfix(
                {k: f"{float(v):.4f}" for k, v in metrics.items() if k == "loss"}
            )

    def on_train_end(self, trainer):
        if self._bar is not None:
            self._bar.close()


class HooksCallback(Callback):
    """Per-parameter-group gradient/param-norm dumps (reference
    ``lightning/neuron_hooks_callback.py:8`` NeuronHooksCallback — activation
    and grad-norm debugging). Computes top-level-group param norms from the
    train state every ``every`` steps and hands them to ``sink`` (default:
    the module logger)."""

    def __init__(self, every: int = 50, sink: Optional[Callable] = None):
        self.every = every
        self.sink = sink or (lambda d: logger.info("param norms: %s", d))

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.every != 0:
            return
        params = trainer.state.params
        groups = params.items() if isinstance(params, dict) else [("params", params)]
        norms = {}
        for name, tree in groups:
            leaves = jax.tree.leaves(tree)
            if leaves:
                # graftlint: ok[GL02] debug hook, gated to every `every`
                # steps — one norm scalar per param group is its contract
                norms[name] = float(
                    jax.numpy.sqrt(
                        sum(jax.numpy.sum(l.astype(jax.numpy.float32) ** 2)
                            for l in leaves)
                    )
                )
        self.sink(norms)


class CheckpointCallback(Callback):
    """Periodic async checkpoint with retention (reference
    lightning/checkpoint_io.py + trainer/checkpoint.py save path).

    ``save_on_end`` writes a final ``step_N`` checkpoint from
    ``on_train_end`` when the last step did not land on the ``every``
    boundary (skipped when one for that step already committed — e.g. the
    graceful-preemption save — and when the trainer halted, which wrote an
    emergency checkpoint instead). The saved ``user_content`` is the
    trainer's full exact-resume payload (step, RNG, data cursor,
    bookkeeping)."""

    def __init__(self, checkpoint_dir: str, every: int = 100,
                 num_kept: Optional[int] = 3, async_save: bool = True,
                 save_on_end: bool = True):
        self.checkpoint_dir = checkpoint_dir
        self.every = every
        self.num_kept = num_kept
        self.async_save = async_save
        self.save_on_end = save_on_end

    def _save(self, trainer, async_save: bool) -> None:
        trainer.save_tagged_checkpoint(
            self.checkpoint_dir, f"step_{trainer.step}",
            num_kept=self.num_kept, async_save=async_save,
        )

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.every != 0:
            return
        self._save(trainer, self.async_save)

    def on_train_end(self, trainer):
        from neuronx_distributed_tpu.trainer.checkpoint import (
            DONE_MARKER,
            create_checkpoint_storage,
            finalize_checkpoints,
        )

        # drain in-flight async saves FIRST: a pending step_N commit must
        # win over (not race) the save_on_end rewrite of the same tag
        finalize_checkpoints()
        if (
            not self.save_on_end
            or trainer.step == 0
            or getattr(trainer, "halt_reason", None) is not None
        ):
            return
        storage = create_checkpoint_storage(self.checkpoint_dir)
        if storage.file_exists(os.path.join(f"step_{trainer.step}", DONE_MARKER)):
            return  # periodic/preemption save already covered this step
        self._save(trainer, async_save=False)
        finalize_checkpoints()


@dataclasses.dataclass
class Trainer:
    """Plain training loop over the jitted SPMD step (the reference's
    Lightning strategy+module+launcher collapse into this), carrying the
    fault-tolerance layer described in the module docstring."""

    model: Any
    optimizer_config: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    callbacks: List[Callback] = dataclasses.field(default_factory=list)
    loss_fn: Optional[Callable] = None
    timeline: Optional[Timeline] = None
    # Pipeline-parallel adapter (e.g. pipeline.llama.LlamaPipelineAdapter).
    # When set, fit() builds the pipelined train step (GPipe scan or explicit
    # 1F1B per the adapter's schedule) instead of the monolithic one — the
    # reference's NxDPPModel wrap inside initialize_parallel_model
    # (trainer/trainer.py:147).
    pipeline: Optional[Any] = None
    # jax.profiler trace directory (reference aux: the neuron-profiler hooks,
    # SURVEY §5 tracing/profiling — device-level truth to pair with the
    # schedule-derived pipeline timeline). Profiles steps [2, 5) of fit().
    profile_dir: Optional[str] = None

    # --- fault tolerance ----------------------------------------------------
    # On-device anomaly guard config (None disables; pipeline adapters build
    # their own step, so the guard covers the monolithic path only).
    anomaly_guard: Optional[AnomalyGuardConfig] = dataclasses.field(
        default_factory=AnomalyGuardConfig
    )
    # Bounded retries for a failed train-step dispatch (serving's defaults).
    dispatch_retry: Optional[RetryPolicy] = None
    # Deterministic chaos source (trainer/faults.py); None = all hooks no-op.
    fault_injector: Optional[Any] = None
    # Where emergency/preemption checkpoints go; falls back to the first
    # CheckpointCallback's directory.
    emergency_dir: Optional[str] = None
    # DEGRADED window after an anomaly skip / dispatch retry.
    degraded_cooldown_steps: int = 20
    # Flight recorder (observability/flight_recorder.py): bounded ring of
    # recent structured events, auto-dumped as a redacted JSON post-mortem
    # when the run halts. None = fit() builds one whose dumps land next to
    # the checkpoints (memory-only when no checkpoint dir is known).
    flight_recorder: Optional[Any] = None
    # Compiled-program ledger (observability/programs.py, ISSUE 12): the
    # train/eval steps register through it — dispatch counts, compile
    # wall, compiler-reported FLOPs/bytes, per-step roofline off the
    # inter-step wall the loop already measures. None = fit() builds one
    # (sharing a MetricsCallback's registry when one is attached).
    program_ledger: Optional[Any] = None
    # Install SIGTERM/SIGINT graceful-preemption handlers during fit()
    # (main thread only; a second signal falls through to the original
    # handler).
    handle_signals: bool = True
    # SDC sentinel (integrity/sentinel.SentinelConfig; None = off). Covers
    # the monolithic step only — pipeline adapters build their own step,
    # exactly like the anomaly guard's scope. Fingerprints are read
    # through the guard's deferred readback, so the host-sync budget
    # stays one device_get call per step with the sentinel fully ON.
    integrity: Optional[Any] = None

    step: int = 0
    state: Any = None
    steps_run: int = 0  # steps executed by the last fit() (excludes resumed ones)
    # Host-mirrored robustness counters. dispatch_retries /
    # emergency_checkpoints / callback_errors accumulate across the
    # Trainer's life; anomaly_skips MIRRORS the checkpoint-carried device
    # counter the budget reads — it continues across resume_from but
    # restarts with the fresh guard state of a new (non-resumed) fit.
    anomaly_skips: int = 0
    dispatch_retries: int = 0
    emergency_checkpoints: int = 0
    callback_errors: int = 0
    # restores that failed digest verification and fell back (ISSUE 20)
    checkpoint_integrity_failures: int = 0
    tokens_seen: int = 0
    train_seconds: float = 0.0
    halt_reason: Optional[str] = None
    preempted: bool = False
    # AOT prewarm manifest recorded by prewarm() BEFORE fit() built the
    # step — consumed right after the train step exists (ISSUE 17)
    _aot_pending: Optional[Any] = dataclasses.field(default=None, repr=False)

    # --- AOT serving (inference/aot.py, ISSUE 17) ---------------------------

    def manifest(self):
        """AOT :class:`~..inference.aot.ProgramManifest` of this trainer's
        compiled programs (train/eval step) — available once fit() has
        run; persist it next to the checkpoints for the next process."""
        if getattr(self, "programs", None) is None:
            raise RuntimeError("manifest() needs a fitted Trainer")
        return self.programs.manifest()

    def prewarm(self, manifest=None, cache_dir: Optional[str] = None) -> dict:
        """Trainer equivalent of ``ServingEngine.prewarm``: point the
        persistent compile cache at ``cache_dir`` (the next compile of a
        known step becomes a disk hit) and, given a manifest (object,
        path, or ``cache_dir/manifest.json``), replay the train/eval-step
        entries with pedigree-faithful dummies so the first real step's
        wall contains zero compiles. The step is built inside fit(), so a
        pre-fit prewarm defers the replay until fit() has built it —
        still BEFORE the first batch dispatches."""
        import os as _os

        from neuronx_distributed_tpu.inference import aot

        if cache_dir is not None:
            aot.enable_persistent_cache(
                _os.path.join(cache_dir, aot.XLA_SUBDIR)
            )
            if manifest is None:
                p = _os.path.join(cache_dir, aot.MANIFEST_NAME)
                if _os.path.exists(p):
                    manifest = aot.ProgramManifest.load(p)
        if isinstance(manifest, (str, _os.PathLike)):
            manifest = aot.ProgramManifest.load(_os.fspath(manifest))
        if manifest is None:
            return {"deferred": False, "replayed": [], "skipped": {}}
        if getattr(self, "_train_step", None) is not None:
            return self._replay_aot_manifest(manifest)
        self._aot_pending = manifest
        return {"deferred": True}

    def _replay_aot_manifest(self, manifest) -> dict:
        from neuronx_distributed_tpu.inference import aot

        live = {
            "train_step": getattr(self, "_train_step", None),
            "eval_step": getattr(self, "_eval_step", None),
        }
        return aot.prewarm_programs(
            manifest, lambda name: live.get(name),
            ledger=self.programs, mode="trace",
            flight=getattr(self, "_flight", None),
        )

    # --- health -------------------------------------------------------------

    def health(self) -> TrainerHealth:
        """Current health (``OK/DEGRADED/HALTED``)."""
        if self.halt_reason is not None:
            return TrainerHealth.HALTED
        last = getattr(self, "_last_fault_step", None)
        if last is not None and self.step - last < self.degraded_cooldown_steps:
            return TrainerHealth.DEGRADED
        return TrainerHealth.OK

    # --- exact-resume payload -----------------------------------------------

    def step_rng(self) -> jax.Array:
        """Deterministic per-step key — ``fold_in(base, step)``. The base
        key is checkpointed, so a resumed run's step keys are identical to
        the uninterrupted run's."""
        return jax.random.fold_in(self._rng_base, self.step)

    def checkpoint_user_content(self, extra: Optional[dict] = None) -> dict:
        """The exact-resume payload every checkpoint carries: step, base
        RNG key, data-iterator cursor, and throughput/step bookkeeping."""
        uc = {
            "step": int(self.step),
            "tokens_seen": int(self.tokens_seen),
            "train_seconds": float(
                self.train_seconds
                + (time.perf_counter() - getattr(self, "_fit_t0", time.perf_counter()))
            ),
            "anomaly_skips": int(self.anomaly_skips),
            "dispatch_retries": int(self.dispatch_retries),
        }
        base = getattr(self, "_rng_base", None)
        if base is not None:
            raw = base
            if jnp.issubdtype(raw.dtype, jax.dtypes.prng_key):
                raw = jax.random.key_data(raw)
            # graftlint: ok[GL02] checkpoint serialization — runs per save,
            # not per step; explicit so guards can tell it from a stray sync
            raw = jax.device_get(raw)
            uc["rng_key"] = np.asarray(raw).astype(np.uint32).tolist()
        src = getattr(self, "_data_source", None)
        if src is not None:
            # a checkpoint written MID-step (emergency halt from a failed
            # dispatch) or while a pulled batch is still PENDING (preemption
            # before the first dispatch — the shape probe was drawn but
            # never trained) must point at the batch the next step was
            # GOING to train on: the live cursor is one ahead of the truth
            if getattr(self, "_mid_step", False) or getattr(
                self, "_pending_untrained", False
            ):
                uc["data_state"] = self._data_state_prepull
            else:
                uc["data_state"] = src.state()
        guard = getattr(self.state, "guard", None) if self.state is not None else None
        if guard is not None:
            # the anomaly-guard carry rides the checkpoint: without it a
            # resumed run re-warms the spike EMA from zero and diverges
            # from the uninterrupted run at the next spike (and the device
            # skips counter the budget reads would restart at 0)
            uc["guard"] = {
                "gnorm_ema": float(np.asarray(guard["gnorm_ema"])),
                "good_steps": int(np.asarray(guard["good_steps"])),
                "skips": int(np.asarray(guard["skips"])),
            }
        if extra:
            uc.update(extra)
        return uc

    def save_tagged_checkpoint(
        self,
        checkpoint_dir: str,
        tag: str,
        *,
        extra: Optional[dict] = None,
        num_kept: Optional[int] = None,
        async_save: bool = False,
    ) -> None:
        """The one save path every tagged checkpoint goes through —
        periodic (:class:`CheckpointCallback`), graceful preemption, and
        emergency halt all write the same item set and the full
        exact-resume ``user_content``, then run the post-save hooks."""
        save_checkpoint(
            checkpoint_dir, tag=tag,
            items={"model": self.state.params, "optimizer": self.state.opt_state},
            user_content=self.checkpoint_user_content(extra),
            num_kept_ckpts=num_kept,
            async_save=async_save,
        )
        inj = self.fault_injector
        if (
            async_save
            and inj is not None
            and (
                getattr(inj, "pending_corruption", lambda _: False)(tag)
                or getattr(inj, "pending_shard_flip", lambda: False)()
            )
        ):
            # a scheduled corrupt_checkpoint or checkpoint_shard flip must
            # hit a COMMITTED save — drain the async commit first
            # (chaos-only; clean saves keep the non-blocking path)
            from neuronx_distributed_tpu.trainer.checkpoint import (
                finalize_checkpoints,
            )

            finalize_checkpoints()
        self.notify_checkpoint_saved(checkpoint_dir, tag)

    def notify_checkpoint_saved(self, checkpoint_dir: str, tag: str) -> None:
        """Post-save hook: timeline instant + fault-injector consultation
        (``corrupt_checkpoint`` fires here)."""
        tl = getattr(self, "_tl", None)
        if tl is not None:
            tl.instant("checkpoint", "trainer", args={"tag": tag})
        self._flight_record("checkpoint", tag=tag, step=self.step)
        if self.fault_injector is not None:
            self.fault_injector.on_checkpoint_saved(checkpoint_dir, tag)

    def _checkpoint_dir(self) -> Optional[str]:
        if self.emergency_dir is not None:
            return self.emergency_dir
        for cb in self.callbacks:
            d = getattr(cb, "checkpoint_dir", None)
            if d is not None:
                return d
        return None

    # --- fault machinery ----------------------------------------------------

    def _flight_record(self, kind: str, **fields) -> None:
        fl = getattr(self, "_flight", None)
        if fl is not None:
            fl.record(kind, **fields)

    def _save_emergency_checkpoint(self, reason: str) -> Optional[str]:
        d = self._checkpoint_dir()
        if d is None:
            logger.warning(
                "halting without an emergency checkpoint — no checkpoint "
                "directory known (set Trainer.emergency_dir)"
            )
            return None
        tag = f"emergency_step_{self.step}"
        self.save_tagged_checkpoint(d, tag, extra={"emergency": reason})
        self.emergency_checkpoints += 1
        self._tl.instant("emergency_checkpoint", "trainer", args={"tag": tag})
        self._flight_record("emergency_checkpoint", tag=tag, dir=d)
        logger.warning("emergency checkpoint '%s' written to %s", tag, d)
        return tag

    def _halt(self, reason: str, save: bool = True) -> None:
        self.halt_reason = reason
        tag = self._save_emergency_checkpoint(reason) if save else None
        self._tl.instant("halted", "trainer", args={"reason": reason})
        # post-mortem: the last N structured events (anomaly skips, dispatch
        # failures, checkpoints, callback errors) + the halt context, dumped
        # atomically next to the checkpoints BEFORE TrainerHalted unwinds —
        # the unattended-death record PRs 3/5 left missing
        fl = getattr(self, "_flight", None)
        if fl is not None:
            fl.record("halt", reason=reason, step=self.step,
                      emergency_tag=tag)
            extra = {
                "step": self.step,
                "emergency_tag": tag,
                "anomaly_skips": self.anomaly_skips,
                "dispatch_retries": self.dispatch_retries,
                "callback_errors": self.callback_errors,
                "tokens_seen": self.tokens_seen,
                "checkpoint_integrity_failures":
                    self.checkpoint_integrity_failures,
            }
            sentinel = getattr(self, "_sentinel", None)
            if sentinel is not None:
                # flat scalars (survive the recorder's depth-3 redaction)
                extra["integrity"] = dict(sentinel.counters)
                extra["integrity"]["quarantined_devices"] = ",".join(
                    str(d) for d in sentinel.quarantined_devices
                )
            # device-efficiency context (ISSUE 12): where HBM went and
            # which programs were hot when training died — flat scalar
            # tables (survive the recorder's depth-3 redaction); cost
            # analysis is NOT started on this error path
            hbm = getattr(self, "hbm", None)
            if hbm is not None:
                extra["hbm"] = hbm.halt_summary()
            programs = getattr(self, "programs", None)
            if programs is not None:
                extra["programs"] = programs.halt_summary()
            fl.dump(reason, extra=extra)
        logger.error("training HALTED: %s", reason)
        raise TrainerHalted(reason, emergency_tag=tag)

    @staticmethod
    def _state_consumed(state) -> bool:
        return any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(state)
        )

    def _dispatch(self, train_step, prepared):
        """Run one train-step dispatch with bounded recovery: a host-side
        failure leaves the donated buffers unconsumed, so the retry runs
        against the last known-good ``(state, step)`` snapshot —
        ``self.state``, unchanged since the last successful step. Bounded
        CONSECUTIVE failures (or consumed buffers, which make retry
        impossible) halt with an emergency checkpoint."""
        policy = self._dispatch_policy
        while True:
            attempt = self._dispatch_attempts
            self._dispatch_attempts += 1
            try:
                if self.fault_injector is not None:
                    self.fault_injector.on_dispatch(attempt)
                out = train_step(self.state, prepared)
                if self._consecutive_dispatch_failures:
                    self._tl.instant(
                        "recovery", "trainer",
                        args={"after_failures": self._consecutive_dispatch_failures,
                              "step": self.step},
                    )
                    self._flight_record(
                        "recovery", step=self.step,
                        after_failures=self._consecutive_dispatch_failures,
                    )
                    self._consecutive_dispatch_failures = 0
                return out
            except KeyboardInterrupt:
                raise
            except (TypeError, ValueError, NotImplementedError):
                raise  # deterministic programming errors — retrying is noise
            except Exception as e:
                n = self._consecutive_dispatch_failures = (
                    self._consecutive_dispatch_failures + 1
                )
                self.dispatch_retries += 1
                self._last_fault_step = self.step
                self._tl.instant(
                    "dispatch_failure", "trainer",
                    args={"error": str(e)[:200], "consecutive": n,
                          "step": self.step},
                )
                self._flight_record("dispatch_failure", step=self.step,
                                    error=str(e), consecutive=n)
                logger.warning(
                    "train-step dispatch failed at step %d (%s: %s) — "
                    "consecutive failure %d/%d",
                    self.step, type(e).__name__, e, n, policy.max_attempts,
                )
                if self._state_consumed(self.state):
                    # the donated buffers are gone: nothing to retry with
                    # and nothing to checkpoint — resume from the last
                    # on-disk checkpoint instead
                    self._halt(
                        f"dispatch failed with consumed donated buffers "
                        f"({type(e).__name__}: {e}) — resume from the last "
                        "checkpoint",
                        save=False,
                    )
                if n >= policy.max_attempts:
                    self._halt(
                        f"{n} consecutive dispatch failures "
                        f"(last: {type(e).__name__}: {e})"
                    )
                # shared decrementing-jitter wait (0-based attempt index)
                time.sleep(policy.wait(n - 1))

    def _account_guard(self) -> None:
        """Budget accounting for the DEFERRED guard flags: reads the
        previous step's ``(good_step, anomaly_skips)`` scalars AFTER the
        next step has been dispatched, so the tiny readback overlaps device
        compute — the clean path adds no stall, and the jitted step itself
        never syncs. Detection therefore lags one step; an anomalous step
        is already harmless (its update was skipped on device).

        The SDC sentinel's fingerprint scalars (ISSUE 20) ride the SAME
        ``device_get`` call: a check step appends a few uint32 leaves to
        this readback, so the pinned one-call-per-step budget holds with
        the sentinel fully ON."""
        pending = self._pending_guard
        ipending = self._pending_integrity
        if pending is None and ipending is None:
            return
        self._pending_guard = None
        self._pending_integrity = None
        guard_leaves = () if pending is None else (pending[1], pending[2])
        fp_leaves = () if ipending is None else tuple(ipending)
        try:
            # graftlint: ok[GL02] the PR 5 deferred guard readback: the
            # PREVIOUS step's tiny flag pair (plus, on sentinel check
            # steps, its uint32 fingerprint scalars), read only after the
            # next step dispatched so it overlaps device compute — tests/
            # trainer/test_faults.py pins it at exactly one get per step
            vals = jax.device_get(guard_leaves + fp_leaves)
        except (KeyboardInterrupt, TrainerHalted):
            raise
        except Exception as e:
            # async dispatch means a DEVICE-side execution failure surfaces
            # here, not at the dispatch call — the step's outputs (now
            # self.state) are poisoned and the donated inputs are gone, so
            # there is nothing to retry or checkpoint: halt for cause
            # instead of leaking a raw backend error past the halt/
            # on_train_end machinery
            self._halt(
                f"train-step execution failed (surfaced at the deferred "
                f"guard readback): {type(e).__name__}: {e} — resume from "
                "the last checkpoint",
                save=False,
            )
        fp_vals = vals[len(guard_leaves):]
        if pending is not None:
            at_step = pending[0]
            good, skips = vals[0], vals[1]
            skips = int(skips)
            if not bool(good):
                self._last_fault_step = self.step
                self._tl.instant(
                    "anomaly_skip", "trainer",
                    args={"step": at_step, "skips": skips},
                )
                self._flight_record("anomaly_skip", step=at_step, skips=skips)
                logger.warning(
                    "anomalous step %d skipped on device (%d skips total)",
                    at_step, skips,
                )
            self.anomaly_skips = skips
            budget = self.anomaly_guard.budget if self.anomaly_guard else None
            if budget is not None and skips > budget:
                self._halt(
                    f"anomaly budget exceeded: {skips} skipped steps > "
                    f"budget {budget}"
                )
        if fp_leaves:
            verdict = self._sentinel.judge(fp_vals)
            if verdict is not None and verdict.detected:
                self._handle_sdc(verdict)

    def _handle_sdc(self, verdict) -> None:
        """A fingerprint check failed: silent corruption is live in the
        TrainState. Fence and continue — quarantine the convicted devices
        in the flight recorder, restore the last verified known-good
        ``(state, step, data cursor, tokens)``, and keep looping (the
        discarded steps re-run deterministically, so the final state is
        bit-identical to a run that never saw the corruption). No rollback
        point → the TrainerHalted/resume contract takes over."""
        s = self._sentinel
        self._last_fault_step = self.step
        convicted = ",".join(str(d) for d in verdict.convicted_devices)
        self._tl.instant(
            "sdc_detected", "trainer",
            args={"step": verdict.step, "mode": verdict.mode,
                  "localized": verdict.localized, "devices": convicted},
        )
        self._flight_record(
            "sdc_detected", step=verdict.step, mode=verdict.mode,
            localized=verdict.localized, devices=convicted,
        )
        for d in verdict.convicted_devices:
            # the quarantine record: which physical device returned wrong
            # bits — the post-mortem's pointer for draining/replacing it
            self._flight_record("device_quarantined", device=int(d),
                                step=verdict.step)
        logger.error(
            "silent data corruption detected at step %d (%s vote%s) — "
            "convicted device(s): %s",
            verdict.step, verdict.mode,
            "" if verdict.localized else ", UNLOCALIZED",
            convicted or "<none>",
        )
        if not s.can_rollback() or self._data_source is None:
            self._halt(
                f"silent data corruption detected at step {verdict.step} "
                f"({verdict.mode} check) with no in-memory rollback point "
                "— resume from the last verified checkpoint"
            )
        rb = s.rollback()
        self.state = rb["state"]
        self.step = rb["step"]
        self.tokens_seen = rb["tokens_seen"]
        if rb["data_state"] is not None:
            self._data_source.restore(rb["data_state"])
            self._data_state_prepull = rb["data_state"]
        # the in-flight guard flags / metrics belong to a discarded step
        self._pending_guard = None
        self._drop_pending_guard = True
        self._flight_record("sdc_rollback", to_step=self.step,
                            detected_at=verdict.step)
        self._tl.instant(
            "sdc_rollback", "trainer",
            args={"to_step": self.step, "detected_at": verdict.step},
        )
        logger.warning(
            "rolled back to verified step %d — re-training the discarded "
            "window (bit-identical by determinism)", self.step,
        )

    def _on_checkpoint_corrupt(self, tag: str, detail: str) -> None:
        """load_checkpoint's on_corrupt hook: a tag failed digest
        verification at resume and was quarantined."""
        self.checkpoint_integrity_failures += 1
        self._last_fault_step = self.step
        self._tl.instant(
            "checkpoint_integrity_failure", "trainer",
            args={"tag": tag, "detail": str(detail)[:200]},
        )
        self._flight_record("checkpoint_integrity_failure", tag=tag,
                            detail=str(detail))
        logger.error(
            "checkpoint '%s' failed integrity verification (%s) — "
            "falling back to the previous completed tag", tag, detail,
        )

    # --- signals ------------------------------------------------------------

    def _install_signal_handlers(self) -> dict:
        self._preempt_signum = None
        if not self.handle_signals:
            return {}
        if threading.current_thread() is not threading.main_thread():
            return {}  # signal.signal is main-thread-only
        orig = {}

        def handler(signum, frame):
            if self._preempt_signum is None:
                self._preempt_signum = signum
                logger.warning(
                    "signal %d received — finishing the in-flight step, "
                    "checkpointing, then exiting cleanly (send again to "
                    "force)", signum,
                )
            else:  # second signal: fall through to the original behavior
                prev = orig.get(signum)
                if prev is None:  # None = handler installed by non-Python
                    prev = _signal.SIG_DFL  # code — closest we can restore
                _signal.signal(signum, prev)
                os.kill(os.getpid(), signum)

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                orig[sig] = _signal.signal(sig, handler)
            except (ValueError, OSError):  # non-main thread or exotic host
                pass
        return orig

    def _restore_signal_handlers(self, orig: dict) -> None:
        for sig, h in orig.items():
            if h is None:
                # signal.signal returned None for a handler installed by
                # non-Python code (embedded interpreter) — there is nothing
                # Python can restore it to; leave ours in place rather
                # than crash the fit epilogue with a TypeError
                continue
            try:
                _signal.signal(sig, h)
            except (ValueError, OSError, TypeError):
                pass

    def _graceful_preempt(self) -> None:
        """The in-flight step finished; write a final tagged checkpoint
        through the done-marker protocol and leave the loop cleanly."""
        self.preempted = True
        d = self._checkpoint_dir()
        tag = f"step_{self.step}"
        if d is not None:
            from neuronx_distributed_tpu.trainer.checkpoint import (
                DONE_MARKER,
                create_checkpoint_storage,
                finalize_checkpoints,
            )

            finalize_checkpoints()  # a pending async save of this tag wins
            storage = create_checkpoint_storage(d)
            if not storage.file_exists(os.path.join(tag, DONE_MARKER)):
                self.save_tagged_checkpoint(
                    d, tag,
                    extra={"preempted": int(self._preempt_signum or 0)},
                )
        self._tl.instant(
            "preempted", "trainer",
            args={"signal": int(self._preempt_signum or 0), "step": self.step},
        )
        self._flight_record("preempted", step=self.step,
                            signal=int(self._preempt_signum or 0))
        logger.warning(
            "preempted by signal %s at step %d — checkpoint %s; exiting "
            "cleanly", self._preempt_signum, self.step,
            tag if d is not None else "SKIPPED (no checkpoint dir)",
        )

    # --- callbacks ------------------------------------------------------------

    def _safe_callback(self, cb, method: str, *args) -> None:
        """One misbehaving callback must not kill the run: exceptions are
        logged with the callback's class name, counted in
        ``callback_errors``, and swallowed — and because each callback is
        isolated individually, ``on_train_end`` still reaches every one."""
        try:
            getattr(cb, method)(*args)
        except (KeyboardInterrupt, TrainerHalted):
            raise
        except Exception as e:
            self.callback_errors += 1
            # a failing callback counts as a fault for health(): a broken
            # CheckpointCallback save means the run is progressing WITHOUT
            # durable checkpoints — unattended monitoring must see
            # DEGRADED, not OK, for as long as the errors keep coming
            self._last_fault_step = self.step
            self._tl.instant(
                "callback_error", "trainer",
                args={"callback": type(cb).__name__, "hook": method,
                      "error": str(e)[:200]},
            )
            self._flight_record("callback_error", callback=type(cb).__name__,
                                hook=method, error=str(e))
            logger.exception(
                "callback %s.%s raised (%s: %s) — training continues",
                type(cb).__name__, method, type(e).__name__, e,
            )

    # --- the loop -------------------------------------------------------------

    def fit(
        self,
        data_iter: Iterable[dict],
        rng_key: jax.Array,
        max_steps: int,
        sample_batch: Optional[dict] = None,
        resume_from: Optional[str] = None,
    ) -> dict:
        """Run ``max_steps`` over ``data_iter`` (an iterable of host batches
        with at least ``input_ids``/``labels``). Returns the last metrics.

        Raises :class:`TrainerHalted` when the anomaly or dispatch-retry
        budget is exhausted (after writing an emergency checkpoint and
        running every callback's ``on_train_end``)."""
        from neuronx_distributed_tpu.parallel import mesh as mesh_lib

        if not mesh_lib.model_parallel_is_initialized():
            # data-parallel-only default (reference neuronx_distributed_config
            # initializes parallel state the same way when sizes are 1)
            mesh_lib.initialize_model_parallel()
        # exact-resume data protocol: the SOURCE object carries the cursor
        self._data_source = (
            data_iter
            if hasattr(data_iter, "state") and hasattr(data_iter, "restore")
            else None
        )
        self._mid_step = False
        self._data_state_prepull = (
            self._data_source.state() if self._data_source is not None else None
        )
        data_iter = iter(data_iter)
        self.steps_run = 0  # per-fit counter (profiler window + throughput)
        self._eval_step = None  # rebuilt lazily against this fit's wiring
        self._eval_prepare = None
        self.halt_reason = None
        self.preempted = False
        self._rng_base = rng_key
        self._dispatch_attempts = 0
        self._consecutive_dispatch_failures = 0
        self._last_fault_step = None
        self._pending_guard = None
        self._pending_integrity = None
        self._drop_pending_guard = False
        self._sentinel = None
        self._dispatch_policy = self.dispatch_retry or RetryPolicy(
            max_attempts=3, first_wait=0.05, min_wait=0.01
        )
        self._tl = tl = self.timeline or Timeline(None)
        if self.flight_recorder is not None:
            self._flight = self.flight_recorder
        else:
            from neuronx_distributed_tpu.observability.flight_recorder import (
                FlightRecorder,
            )

            # default recorder: post-mortems land next to the checkpoints
            # (memory-only when no directory is known — last_postmortem
            # still carries the ring for an operator holding the object)
            self._flight = FlightRecorder(
                dump_dir=self._checkpoint_dir(), subsystem="trainer"
            )
        # compiled-program ledger (ISSUE 12): created once per Trainer so
        # re-fits ACCUMULATE (a rebuilt train step wraps the same record);
        # rides a MetricsCallback's registry when one is attached so the
        # per-step achieved-FLOPs/MFU gauges share the scrape surface
        if self.program_ledger is None:
            from neuronx_distributed_tpu.observability.callback import (
                MetricsCallback,
            )
            from neuronx_distributed_tpu.observability.programs import (
                ProgramLedger,
            )

            reg = None
            for cb in self.callbacks:
                if isinstance(cb, MetricsCallback):
                    reg = cb.registry
                    break
            self.program_ledger = ProgramLedger(
                registry=reg, prefix="train", subsystem="trainer",
                timeline=tl,
            )
        self.programs = self.program_ledger
        inj = self.fault_injector
        first = sample_batch if sample_batch is not None else next(data_iter)
        optimizer = make_optimizer(self.optimizer_config)
        if self.pipeline is not None and self.optimizer_config.grad_accum_steps > 1:
            raise ValueError(
                "grad_accum_steps does not apply under a pipeline adapter — "
                "pipeline microbatches already accumulate; raise "
                "num_microbatches instead"
            )
        guard_cfg = self.anomaly_guard if self.pipeline is None else None
        if self.pipeline is not None and not hasattr(jax, "shard_map"):
            # fail fast with the compat gate's message instead of burning
            # dispatch retries on a deterministic trace-time error
            raise RuntimeError(
                "pipeline parallelism requires jax >= 0.5 (this jax's "
                "partial-manual CollectivePermute lowering crashes XLA); "
                "run with pp=1 on this installation"
            )
        if self.pipeline is not None:
            self.state, train_step, engine = self.pipeline.build_state_and_step(
                self.model, optimizer, rng_key, first["input_ids"],
                zero1=self.optimizer_config.zero1,
                max_grad_norm=self.optimizer_config.max_grad_norm,
            )
            self._pipeline_engine = engine
            prepare = self.pipeline.prepare_batch
        else:
            self.state, p_sh, s_sh = create_train_state(
                self.model, optimizer, rng_key, first["input_ids"],
                zero1=self.optimizer_config.zero1,
            )
            accum = self.optimizer_config.grad_accum_steps
            train_step = build_train_step(
                self.model, optimizer, p_sh, s_sh,
                max_grad_norm=self.optimizer_config.max_grad_norm,
                loss_fn=self.loss_fn,
                grad_accum_steps=accum,
                anomaly_guard=guard_cfg,
            )
            if guard_cfg is not None:
                self.state = self.state.replace(guard=init_anomaly_guard_state())
            if accum > 1:
                from neuronx_distributed_tpu.pipeline.model import (
                    microbatch,
                    shard_microbatched_batch,
                )

                def prepare(batch):
                    return shard_microbatched_batch(microbatch(batch, accum))
            else:
                prepare = shard_batch
        # ledger proxy: dispatch counts + compile detection, zero syncs
        # (the proxy forwards _cache_size(), so the compile-budget guard
        # below keeps reading through)
        train_step = self.programs.wrap("train_step", train_step)
        # exposed for the compile-budget guard (one program must serve clean
        # AND anomalous batches — tests/trainer/test_faults.py)
        self._train_step = train_step
        if self._aot_pending is not None:
            # deferred AOT prewarm (ISSUE 17): the step now exists — eat
            # the compile (a disk hit under the persistent cache) BEFORE
            # the first batch dispatches
            pending, self._aot_pending = self._aot_pending, None
            self._replay_aot_manifest(pending)
        # HBM ledger (ISSUE 12): the trainer's static residents as weakref
        # closures over the live TrainState — params, optimizer state, the
        # anomaly-guard carry — reconciled against device limits
        from neuronx_distributed_tpu.observability.hbm import (
            HBMLedger,
            tree_nbytes,
        )
        from neuronx_distributed_tpu.observability.programs import weak_reader

        self.hbm = HBMLedger(view=self.programs.view)

        def _res(fn):
            # state=None (pre-fit reads) falls to 0 via the resident
            # reader's exception guard — tree_nbytes(None.state) raises
            return weak_reader(self, fn)

        self.hbm.add_resident("params", _res(
            lambda t: tree_nbytes(t.state.params)
        ))
        self.hbm.add_resident("opt_state", _res(
            lambda t: tree_nbytes(t.state.opt_state)
        ))
        self.hbm.add_resident("anomaly_guard", _res(
            lambda t: tree_nbytes(t.state.guard)
        ))
        # SDC sentinel (ISSUE 20): jitted fingerprint + state-copy programs
        # registered through the ledger like every other program; the
        # sentinel itself never syncs — its scalars ride _account_guard's
        # one deferred device_get
        if self.integrity is not None and self.pipeline is not None:
            logger.warning(
                "integrity sentinel covers the monolithic step only — "
                "disabled under a pipeline adapter (like the anomaly guard)"
            )
        elif self.integrity is not None:
            from neuronx_distributed_tpu.integrity.sentinel import (
                TrainerSentinel,
            )
            from neuronx_distributed_tpu.utils.fingerprint import (
                tree_fingerprint,
            )

            _jit_fp = self.programs.wrap(
                "integrity_fingerprint",
                jax.jit(lambda t: tree_fingerprint(t)),
            )
            dp_size = mesh_lib.get_data_parallel_size()
            _mode = self.integrity.mode
            if _mode == "auto":
                _mode = "vote" if dp_size > 1 else "canary"
            if _mode == "vote" and dp_size > 1:
                # ZeRO-1 shards opt-state leaves over the dp axes; a
                # fingerprint touching such a leaf needs a CROSS-replica
                # reduction, and that one collective uniformizes the whole
                # combined scalar — every device reports the same value
                # even when one replica's (replicated!) params copy is
                # corrupt, blinding the vote entirely. Strip dp-sharded
                # leaves from the vote fingerprint; they are covered by
                # checkpoint shard digests instead. Shardings are stable
                # across steps, so the stripped structure jit-caches once.
                from neuronx_distributed_tpu.parallel.mesh import DATA_AXES

                _dp_names = set(DATA_AXES)

                def _dp_sharded(leaf):
                    spec = getattr(
                        getattr(leaf, "sharding", None), "spec", None
                    )
                    if spec is None:
                        return False
                    names = set()
                    for entry in spec:
                        if entry is None:
                            continue
                        if isinstance(entry, (tuple, list)):
                            names.update(entry)
                        else:
                            names.add(entry)
                    return bool(names & _dp_names)

                def fp_fn(tree):
                    return _jit_fp(jax.tree.map(
                        lambda l: None if _dp_sharded(l) else l, tree
                    ))
            else:
                fp_fn = _jit_fp
            copy_fn = self.programs.wrap(
                "integrity_copy",
                jax.jit(lambda t: jax.tree.map(jnp.copy, t)),
            )
            self._sentinel = TrainerSentinel(
                self.integrity,
                dp_size=mesh_lib.get_data_parallel_size(),
                fingerprint_fn=fp_fn,
                copy_fn=copy_fn,
            )
            # the retained known-good/candidate snapshots are real HBM:
            # account for them next to params/opt-state
            self.hbm.add_resident("integrity_snapshots", _res(
                lambda t: sum(
                    tree_nbytes(s) for s in t._sentinel.snapshot_states()
                )
            ))
        pending = first if sample_batch is None else None
        # the probe pull advanced the cursor past a batch nothing has
        # trained on yet — checkpoints written before it is consumed must
        # save the pre-pull cursor (checkpoint_user_content)
        self._pending_untrained = pending is not None
        if resume_from is not None:
            from neuronx_distributed_tpu.trainer.checkpoint import (
                latest_checkpoint_tag,
                load_checkpoint,
            )

            # resolve the newest COMPLETED tag once (this walk also repairs
            # a corrupt `newest` pointer) and hand it to load_checkpoint —
            # passing no tag would redo the same walk
            tag = latest_checkpoint_tag(resume_from)
            if tag is not None:
                items, user_content, tag = load_checkpoint(
                    resume_from,
                    tag=tag,
                    items_target={
                        "model": self.state.params,
                        "optimizer": self.state.opt_state,
                    },
                    # digest verification with quarantine-and-fall-back
                    # (ISSUE 20): never donate restored garbage
                    on_corrupt=self._on_checkpoint_corrupt,
                )
                if not hasattr(jax, "shard_map"):
                    # jax < 0.5 only: a persistent-cache-deserialized CPU
                    # executable corrupts the heap when it DONATES buffers
                    # that tensorstore materialized (reproduced: resume +
                    # warm compilation cache + first dispatch). Re-own the
                    # restored trees in fresh XLA buffers — jnp.copy is
                    # bit-exact, so resume stays bit-identical.
                    items = jax.tree.map(jnp.copy, items)
                self.state = self.state.replace(
                    params=items["model"], opt_state=items["optimizer"]
                )
                uc = user_content or {}
                self.step = int(uc.get("step", 0))
                # the device step scalar drives nothing numerically (the LR
                # schedule reads the optimizer count) but must agree for
                # bit-identical bookkeeping
                self.state = self.state.replace(
                    step=jax.device_put(
                        jnp.asarray(self.step, self.state.step.dtype),
                        jax.sharding.NamedSharding(
                            mesh_lib.get_mesh(),
                            jax.sharding.PartitionSpec(),
                        ),
                    )
                )
                if uc.get("rng_key") is not None:
                    self._rng_base = jnp.asarray(uc["rng_key"], jnp.uint32)
                self.tokens_seen = int(uc.get("tokens_seen", self.tokens_seen))
                self.train_seconds = float(
                    uc.get("train_seconds", self.train_seconds)
                )
                gc = uc.get("guard")
                if gc is not None and self.state.guard is not None:
                    # restore the anomaly-guard carry (EMA, warmup count,
                    # device skips counter) so spike detection and budget
                    # accounting continue exactly where the interrupted
                    # run left off — built by the same owner as the fresh
                    # tree so the layout always matches what the jitted
                    # step was traced with
                    self.state = self.state.replace(
                        guard=init_anomaly_guard_state(gc)
                    )
                    self.anomaly_skips = int(gc["skips"])
                self.dispatch_retries = int(
                    uc.get("dispatch_retries", self.dispatch_retries)
                )
                ds = uc.get("data_state")
                if ds is not None and self._data_source is not None:
                    self._data_source.restore(ds)
                    self._data_state_prepull = ds
                    # the shape-probe batch was drawn from the PRE-restore
                    # cursor — drop it; the next pull follows the cursor
                    pending = None
                    self._pending_untrained = False
                logger.info("resumed from '%s' at step %d", tag, self.step)
        if self._sentinel is not None:
            # first known-good point: the verified state this fit starts
            # from (fresh init, or a digest-verified checkpoint restore);
            # the cursor pairs it with the batch step self.step will pull
            self._sentinel.set_baseline(
                self.state, self.step,
                self._data_state_prepull
                if self._data_source is not None else None,
                self.tokens_seen,
            )
        meter = ThroughputMeter(batch_size=first["input_ids"].shape[0])
        # shape is host metadata on np AND jax arrays — np.asarray here used
        # to copy the whole batch to host just to read it (GL02-class bug)
        batch_tokens = int(np.prod(first["input_ids"].shape))
        for cb in self.callbacks:
            self._safe_callback(cb, "on_train_start", self)
        metrics = {}
        profiling = False
        self._fit_t0 = time.perf_counter()
        self._step_wall_t0 = time.perf_counter()
        orig_handlers = self._install_signal_handlers()
        halted: Optional[TrainerHalted] = None
        error: Optional[BaseException] = None
        try:
            while True:
                while self.step < max_steps:
                    if inj is not None:
                        inj.on_step_start(self.step)
                    if self._preempt_signum is not None:
                        self._graceful_preempt()
                        break
                    if pending is not None:
                        batch = pending
                        pending = None
                        # the probe batch is now entering training; from here
                        # _mid_step/_data_state_prepull carry the truth
                        self._pending_untrained = False
                    else:
                        if self._data_source is not None:
                            self._data_state_prepull = self._data_source.state()
                        batch = next(data_iter)
                    # the batch has left the iterator: from here until the
                    # dispatch lands, any exit (corrupt_batch raising, profiler
                    # failure, dispatch halt) must checkpoint the PRE-pull
                    # cursor or resume would silently skip this batch
                    self._mid_step = True
                    if inj is not None:
                        batch = inj.corrupt_batch(self.step, batch)
                    if self.profile_dir is not None:
                        if self.steps_run == 2 and not profiling:
                            jax.profiler.start_trace(self.profile_dir)
                            profiling = True
                        elif self.steps_run == 5 and profiling:
                            jax.profiler.stop_trace()
                            profiling = False
                    prepared = prepare(batch)
                    if (
                        self._sentinel is not None
                        and self._sentinel.wants_pre_copy(self.step)
                    ):
                        # canary mode: retain the pre-step state + batch so
                        # post_dispatch can re-execute this exact step
                        self._sentinel.pre_dispatch(self.state, prepared)
                    with tl.event("train_step"):
                        self.state, metrics = self._dispatch(
                            train_step, prepared
                        )
                    if inj is not None and hasattr(inj, "on_state"):
                        # chaos (ISSUE 20): scheduled silent bit flips land on
                        # the live state here — after dispatch, before the
                        # sentinel's check — so detection latency is measured
                        # from the step the corruption actually struck
                        self.state = inj.on_state(self.step, self.state)
                    self._mid_step = False
                    self.step += 1
                    self.steps_run += 1
                    self.tokens_seen += batch_tokens
                    # per-step roofline feed: the inter-step wall (host clock
                    # the loop already owns — dispatch is async, so steady-state
                    # iteration time IS the step wall). The first iteration and
                    # any compile-bearing step are skipped so MFU never
                    # averages in trace+compile time
                    now_wall = time.perf_counter()
                    if self.steps_run > 1 and not getattr(
                        train_step, "last_call_compiled", True
                    ):
                        self.programs.observe_wall(
                            "train_step", now_wall - self._step_wall_t0
                        )
                    self._step_wall_t0 = now_wall
                    # budget-check the PREVIOUS step's guard flags now that this
                    # step is dispatched — the readback overlaps device compute
                    self._account_guard()
                    metrics = dict(metrics)
                    metrics["throughput_seq_s"] = meter.update()
                    metrics["dispatch_retries"] = self.dispatch_retries
                    metrics["emergency_checkpoints"] = self.emergency_checkpoints
                    metrics["callback_errors"] = self.callback_errors
                    # a rollback inside _account_guard discarded this step —
                    # its metrics/flags describe state that no longer exists
                    sdc_rolled = self._drop_pending_guard
                    self._drop_pending_guard = False
                    if guard_cfg is not None and not sdc_rolled:
                        self._pending_guard = (
                            self.step - 1,
                            metrics["good_step"],
                            metrics["anomaly_skips"],
                        )
                    if (
                        self._sentinel is not None
                        and not sdc_rolled
                        and self._sentinel.is_check_step(self.step - 1)
                    ):
                        # stage this check's fingerprint scalars; they ride
                        # the NEXT _account_guard's single device_get
                        self._pending_integrity = self._sentinel.post_dispatch(
                            train_step, self.state, self.step,
                            self._data_source.state()
                            if self._data_source is not None else None,
                            self.tokens_seen,
                        )
                    for cb in self.callbacks:
                        self._safe_callback(cb, "on_step_end", self, metrics)
                    if self._preempt_signum is not None:
                        self._graceful_preempt()
                        break
                # the final step's flags (and any staged fingerprint): a
                # sentinel rollback HERE lowers self.step below max_steps, so
                # the outer loop re-enters training and the run still
                # completes its full schedule bit-identically
                self._account_guard()
                if self.preempted or self.step >= max_steps:
                    break
        except TrainerHalted as e:
            halted = e
        except KeyboardInterrupt:
            raise  # force-exit: skip the epilogue, the user wants OUT now
        except BaseException as e:
            # any other failure (preemption-save IOError, deterministic
            # dispatch error, ...) still owes the callbacks their
            # on_train_end (TensorBoard flush, async-save drain) and the
            # timeline its save — run the epilogue, then re-raise
            error = e
        finally:
            self.train_seconds += time.perf_counter() - self._fit_t0
            # re-anchor so later checkpoint_user_content calls (e.g. the
            # save_on_end path) don't double-count the elapsed wall
            self._fit_t0 = time.perf_counter()
            self._restore_signal_handlers(orig_handlers)
            if profiling:
                jax.profiler.stop_trace()
        for cb in self.callbacks:
            self._safe_callback(cb, "on_train_end", self)
        tl.save()
        if error is not None:
            raise error
        if halted is not None:
            raise halted
        return metrics

    def evaluate(self, data_iter: Iterable[dict], max_steps: int) -> dict:
        """Mean loss over ``max_steps`` eval batches with the CURRENT params,
        no updates (the reference's Lightning validation loop). Requires a
        prior fit() (the jitted loss reuses its model/loss wiring)."""
        if self.state is None:
            raise RuntimeError("evaluate() needs a fitted Trainer (state is None)")
        if getattr(self, "_eval_step", None) is None:
            if self.pipeline is not None:
                loss_fn = self._pipeline_engine.loss_fn
                self._eval_prepare = self.pipeline.prepare_batch
            else:
                from functools import partial

                from neuronx_distributed_tpu.trainer.trainer import default_loss_fn

                loss_fn = self.loss_fn or partial(default_loss_fn, self.model)
                self._eval_prepare = shard_batch
            # cached: a fresh jit wrapper per call would retrace every time
            self._eval_step = jax.jit(loss_fn)
            if getattr(self, "programs", None) is not None:
                self._eval_step = self.programs.wrap(
                    "eval_step", self._eval_step
                )
        data_iter = iter(data_iter)
        total, n = 0.0, 0
        while n < max_steps:
            try:
                batch = next(data_iter)  # never pull past max_steps
            except StopIteration:
                break
            # graftlint: ok[GL02] eval loop: one loss scalar per batch is
            # its contract (no overlap to protect — nothing else is queued)
            total += float(
                jax.device_get(
                    self._eval_step(self.state.params, self._eval_prepare(batch))
                )
            )
            n += 1
        if n == 0:
            raise ValueError("evaluate(): data_iter yielded no batches")
        return {"eval_loss": total / n, "eval_steps": n}

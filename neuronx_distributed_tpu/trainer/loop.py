"""High-level training loop with callbacks (reference:
``src/neuronx_distributed/lightning/`` — ``NeuronXLAStrategy``,
``NeuronLTModule``, the rank-0 TensorBoard logger with step gating
(logger.py:24), TQDM bar, and ``NeuronHooksCallback``; plus the examples'
``Throughput`` moving-average meter, training_utils.py:338).

Lightning's role in the reference — wiring parallel init, precision, the
train loop, logging, and checkpoint IO — collapses here into one plain
``Trainer`` class over the jitted train step. Callbacks get the same hook
points the reference's Lightning plugins use."""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Iterable, List, Optional

import jax

from neuronx_distributed_tpu.trainer.checkpoint import save_checkpoint
from neuronx_distributed_tpu.trainer.trainer import (
    OptimizerConfig,
    build_train_step,
    create_train_state,
    make_optimizer,
    shard_batch,
)
from neuronx_distributed_tpu.utils.logger import get_logger
from neuronx_distributed_tpu.utils.timeline import Timeline

logger = get_logger(__name__)


class Callback:
    """Hook points (reference: Lightning callback surface used by NxD)."""

    def on_train_start(self, trainer: "Trainer") -> None: ...

    def on_step_end(self, trainer: "Trainer", metrics: dict) -> None: ...

    def on_train_end(self, trainer: "Trainer") -> None: ...


class ThroughputMeter:
    """Moving-average seqs/s over the last N steps (reference Throughput,
    examples/training/llama/training_utils.py:338)."""

    def __init__(self, batch_size: int, window: int = 10):
        self.batch_size = batch_size
        self.window = window
        self._times: deque = deque(maxlen=window + 1)
        self.throughput = 0.0

    def update(self) -> float:
        self._times.append(time.perf_counter())
        if len(self._times) >= 2:
            dt = self._times[-1] - self._times[0]
            steps = len(self._times) - 1
            self.throughput = self.batch_size * steps / max(dt, 1e-9)
        return self.throughput


class MetricsLogger(Callback):
    """Rank-0 step-gated metric logging, optionally into TensorBoard
    (reference lightning/logger.py:24 NeuronTensorBoardLogger)."""

    def __init__(self, log_every: int = 10, tensorboard_dir: Optional[str] = None):
        self.log_every = log_every
        self._tb = None
        if tensorboard_dir is not None:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # tensorboard is optional
                logger.warning("tensorboard unavailable (%s); file logging only", e)

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.log_every != 0:
            return
        scalars = {k: float(v) for k, v in metrics.items()}
        logger.info(
            "step %d: %s", trainer.step,
            " ".join(f"{k}={v:.4f}" for k, v in scalars.items()),
        )
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, trainer.step)

    def on_train_end(self, trainer):
        if self._tb is not None:
            self._tb.flush()


class ProgressBar(Callback):
    """TQDM-style progress (reference: the Lightning TQDM bar plugin). Falls
    back to plain line logging when tqdm is unavailable."""

    def __init__(self, total_steps: Optional[int] = None):
        self.total_steps = total_steps
        self._bar = None

    def on_train_start(self, trainer):
        try:
            from tqdm import tqdm

            self._bar = tqdm(total=self.total_steps, desc="train", unit="step")
        except Exception:
            self._bar = None

    def on_step_end(self, trainer, metrics):
        if self._bar is not None:
            self._bar.update(1)
            self._bar.set_postfix(
                {k: f"{float(v):.4f}" for k, v in metrics.items() if k == "loss"}
            )

    def on_train_end(self, trainer):
        if self._bar is not None:
            self._bar.close()


class HooksCallback(Callback):
    """Per-parameter-group gradient/param-norm dumps (reference
    ``lightning/neuron_hooks_callback.py:8`` NeuronHooksCallback — activation
    and grad-norm debugging). Computes top-level-group param norms from the
    train state every ``every`` steps and hands them to ``sink`` (default:
    the module logger)."""

    def __init__(self, every: int = 50, sink: Optional[Callable] = None):
        self.every = every
        self.sink = sink or (lambda d: logger.info("param norms: %s", d))

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.every != 0:
            return
        params = trainer.state.params
        groups = params.items() if isinstance(params, dict) else [("params", params)]
        norms = {}
        for name, tree in groups:
            leaves = jax.tree.leaves(tree)
            if leaves:
                norms[name] = float(
                    jax.numpy.sqrt(
                        sum(jax.numpy.sum(l.astype(jax.numpy.float32) ** 2)
                            for l in leaves)
                    )
                )
        self.sink(norms)


class CheckpointCallback(Callback):
    """Periodic async checkpoint with retention (reference
    lightning/checkpoint_io.py + trainer/checkpoint.py save path)."""

    def __init__(self, checkpoint_dir: str, every: int = 100,
                 num_kept: Optional[int] = 3, async_save: bool = True):
        self.checkpoint_dir = checkpoint_dir
        self.every = every
        self.num_kept = num_kept
        self.async_save = async_save

    def on_step_end(self, trainer, metrics):
        if trainer.step % self.every != 0:
            return
        save_checkpoint(
            self.checkpoint_dir,
            tag=f"step_{trainer.step}",
            items={"model": trainer.state.params, "optimizer": trainer.state.opt_state},
            user_content={"step": trainer.step},
            num_kept_ckpts=self.num_kept,
            async_save=self.async_save,
        )

    def on_train_end(self, trainer):
        from neuronx_distributed_tpu.trainer.checkpoint import finalize_checkpoints

        finalize_checkpoints()


@dataclasses.dataclass
class Trainer:
    """Plain training loop over the jitted SPMD step (the reference's
    Lightning strategy+module+launcher collapse into this)."""

    model: Any
    optimizer_config: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    callbacks: List[Callback] = dataclasses.field(default_factory=list)
    loss_fn: Optional[Callable] = None
    timeline: Optional[Timeline] = None
    # Pipeline-parallel adapter (e.g. pipeline.llama.LlamaPipelineAdapter).
    # When set, fit() builds the pipelined train step (GPipe scan or explicit
    # 1F1B per the adapter's schedule) instead of the monolithic one — the
    # reference's NxDPPModel wrap inside initialize_parallel_model
    # (trainer/trainer.py:147).
    pipeline: Optional[Any] = None
    # jax.profiler trace directory (reference aux: the neuron-profiler hooks,
    # SURVEY §5 tracing/profiling — device-level truth to pair with the
    # schedule-derived pipeline timeline). Profiles steps [2, 5) of fit().
    profile_dir: Optional[str] = None

    step: int = 0
    state: Any = None
    steps_run: int = 0  # steps executed by the last fit() (excludes resumed ones)

    def fit(
        self,
        data_iter: Iterable[dict],
        rng_key: jax.Array,
        max_steps: int,
        sample_batch: Optional[dict] = None,
        resume_from: Optional[str] = None,
    ) -> dict:
        """Run ``max_steps`` over ``data_iter`` (an iterable of host batches
        with at least ``input_ids``/``labels``). Returns the last metrics."""
        from neuronx_distributed_tpu.parallel import mesh as mesh_lib

        if not mesh_lib.model_parallel_is_initialized():
            # data-parallel-only default (reference neuronx_distributed_config
            # initializes parallel state the same way when sizes are 1)
            mesh_lib.initialize_model_parallel()
        data_iter = iter(data_iter)
        self.steps_run = 0  # per-fit counter (profiler window + throughput)
        self._eval_step = None  # rebuilt lazily against this fit's wiring
        self._eval_prepare = None
        first = sample_batch if sample_batch is not None else next(data_iter)
        optimizer = make_optimizer(self.optimizer_config)
        if self.pipeline is not None and self.optimizer_config.grad_accum_steps > 1:
            raise ValueError(
                "grad_accum_steps does not apply under a pipeline adapter — "
                "pipeline microbatches already accumulate; raise "
                "num_microbatches instead"
            )
        if self.pipeline is not None:
            self.state, train_step, engine = self.pipeline.build_state_and_step(
                self.model, optimizer, rng_key, first["input_ids"],
                zero1=self.optimizer_config.zero1,
                max_grad_norm=self.optimizer_config.max_grad_norm,
            )
            self._pipeline_engine = engine
            prepare = self.pipeline.prepare_batch
        else:
            self.state, p_sh, s_sh = create_train_state(
                self.model, optimizer, rng_key, first["input_ids"],
                zero1=self.optimizer_config.zero1,
            )
            accum = self.optimizer_config.grad_accum_steps
            train_step = build_train_step(
                self.model, optimizer, p_sh, s_sh,
                max_grad_norm=self.optimizer_config.max_grad_norm,
                loss_fn=self.loss_fn,
                grad_accum_steps=accum,
            )
            if accum > 1:
                from neuronx_distributed_tpu.pipeline.model import (
                    microbatch,
                    shard_microbatched_batch,
                )

                def prepare(batch):
                    return shard_microbatched_batch(microbatch(batch, accum))
            else:
                prepare = shard_batch
        if resume_from is not None:
            from neuronx_distributed_tpu.trainer.checkpoint import (
                latest_checkpoint_tag,
                load_checkpoint,
            )

            if latest_checkpoint_tag(resume_from) is not None:
                items, user_content, tag = load_checkpoint(
                    resume_from,
                    items_target={
                        "model": self.state.params,
                        "optimizer": self.state.opt_state,
                    },
                )
                self.state = self.state.replace(
                    params=items["model"], opt_state=items["optimizer"]
                )
                self.step = int((user_content or {}).get("step", 0))
                logger.info("resumed from '%s' at step %d", tag, self.step)
        meter = ThroughputMeter(batch_size=first["input_ids"].shape[0])
        for cb in self.callbacks:
            cb.on_train_start(self)
        tl = self.timeline or Timeline(None)
        metrics = {}
        pending = first if sample_batch is None else None
        profiling = False
        while self.step < max_steps:
            batch = pending if pending is not None else next(data_iter)
            pending = None
            if self.profile_dir is not None:
                if self.steps_run == 2 and not profiling:
                    jax.profiler.start_trace(self.profile_dir)
                    profiling = True
                elif self.steps_run == 5 and profiling:
                    jax.profiler.stop_trace()
                    profiling = False
            with tl.event("train_step"):
                self.state, metrics = train_step(self.state, prepare(batch))
            self.step += 1
            self.steps_run += 1
            metrics = dict(metrics)
            metrics["throughput_seq_s"] = meter.update()
            for cb in self.callbacks:
                cb.on_step_end(self, metrics)
        if profiling:
            jax.profiler.stop_trace()
        for cb in self.callbacks:
            cb.on_train_end(self)
        tl.save()
        return metrics

    def evaluate(self, data_iter: Iterable[dict], max_steps: int) -> dict:
        """Mean loss over ``max_steps`` eval batches with the CURRENT params,
        no updates (the reference's Lightning validation loop). Requires a
        prior fit() (the jitted loss reuses its model/loss wiring)."""
        if self.state is None:
            raise RuntimeError("evaluate() needs a fitted Trainer (state is None)")
        if getattr(self, "_eval_step", None) is None:
            if self.pipeline is not None:
                loss_fn = self._pipeline_engine.loss_fn
                self._eval_prepare = self.pipeline.prepare_batch
            else:
                from functools import partial

                from neuronx_distributed_tpu.trainer.trainer import default_loss_fn

                loss_fn = self.loss_fn or partial(default_loss_fn, self.model)
                self._eval_prepare = shard_batch
            # cached: a fresh jit wrapper per call would retrace every time
            self._eval_step = jax.jit(loss_fn)
        data_iter = iter(data_iter)
        total, n = 0.0, 0
        while n < max_steps:
            try:
                batch = next(data_iter)  # never pull past max_steps
            except StopIteration:
                break
            total += float(self._eval_step(self.state.params, self._eval_prepare(batch)))
            n += 1
        if n == 0:
            raise ValueError("evaluate(): data_iter yielded no batches")
        return {"eval_loss": total / n, "eval_steps": n}

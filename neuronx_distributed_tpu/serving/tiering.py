"""Host-RAM page tier behind the device page pool (ISSUE 19).

The paged KV pool and prefix cache are HBM-bound; production prefix working
sets (system prompts x tenants x long docs) are not. Without a second tier a
cold prefix entry under page pressure is simply evicted and its prefill work
redone on the next hit — an eviction CLIFF at device pool capacity.
:class:`HostPageStore` turns that into a SLOPE: the engine's reclaim valve
spills a cold entry's pages here (one batched device->host pull of the raw
pool blocks — k/v pages plus any quantized scale siblings, in pool storage
form), and the admission pre-pass prefetches matched pages back device-side
while the hitting request still queues, overlapped with the current decode
chunk's device time.

Integrity contract: every spilled page carries a content fingerprint
(CRC-32 over its blocks' bytes, computed at put time from the same host
copy that is stored). ``verify()`` recomputes it host-side before a
prefetch writes anything back to the pool — a corrupted host page (bit
rot, a chaos schedule's ``poison_host_page``) is rejected and the engine
falls back to a full prefill, which is bit-identical by construction: K/V
content is position-relative, so re-prefilling the same tokens rebuilds
the same pages.

Everything in this module is host-side numpy over host-resident blocks —
zero device work, zero syncs (graftlint GL02 lists this module; the one
device->host transfer of the spill path lives in
``PagedCacheManager.spill_pages`` behind an explicit pragma).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from neuronx_distributed_tpu.utils.fingerprint import page_fingerprint

__all__ = ["HostPageStore"]

# the CRC chain moved to utils/fingerprint.py (one owner for every
# integrity hash); this alias keeps the module's call sites and the
# byte-identity of pre-refactor spilled-page fingerprints
_page_fingerprint = page_fingerprint


class _HostPage:
    __slots__ = ("pid", "blocks", "fingerprint", "nbytes")

    def __init__(self, pid: int, blocks, fingerprint: int, nbytes: int):
        self.pid = pid          # the device page id this was spilled from
        self.blocks = blocks    # [(path_keys, np block)] — page axis size 1
        self.fingerprint = fingerprint
        self.nbytes = nbytes


class HostPageStore:
    """Bounded host-RAM store of spilled KV pool pages.

    Pages are keyed by MINTED host ids (monotone — device page ids recycle
    the moment a spill frees them, so they cannot be the store's key; each
    record still carries the device pid it was spilled from for the
    post-mortem story). A page is a list of ``(path_keys, block)`` pairs —
    one per k/v pool leaf including quantized scale siblings, page axis
    kept at size 1 — so a multi-page fetch is a plain per-leaf
    ``np.concatenate`` in the exact layout ``PagedCacheManager``'s import
    program scatters back.

    ``max_pages`` bounds host bytes the same way the pool bounds HBM: a
    spill that does not fit is the caller's cue to degrade to plain
    eviction (the pre-tiering behavior) — the store never grows past its
    budget and never throws for being full.
    """

    def __init__(self, max_pages: int):
        if max_pages < 1:
            raise ValueError(f"max_pages must be >= 1, got {max_pages}")
        self.max_pages = max_pages
        self._pages: "OrderedDict[int, _HostPage]" = OrderedDict()
        self._next_id = 1
        # lifetime counters (the engine's metrics read events as they
        # happen; these totals feed summary()/halt post-mortems)
        self.pages_spilled_total = 0
        self.pages_fetched_total = 0
        self.pages_dropped_total = 0
        self.spill_bytes_total = 0
        self.fetch_bytes_total = 0
        self.verify_failures_total = 0

    # --- capacity ------------------------------------------------------------

    @property
    def used_pages(self) -> int:
        return len(self._pages)

    @property
    def free_pages(self) -> int:
        return self.max_pages - len(self._pages)

    @property
    def nbytes(self) -> int:
        """Host bytes currently resident — the HBM ledger's host-tier
        resident source (host metadata, no device involved)."""
        return sum(p.nbytes for p in self._pages.values())

    def __len__(self) -> int:
        return len(self._pages)

    # --- spill / fetch -------------------------------------------------------

    def put(self, pids: Sequence[int], items) -> Tuple[int, ...]:
        """Store ``len(pids)`` spilled pages. ``items`` is the spill
        pull's per-leaf block list ``[(path_keys, np block)]`` with the
        page axis (``block.ndim - 4``) of size ``len(pids)`` — exactly
        what ``PagedCacheManager.spill_pages`` returns. Splits per page,
        fingerprints each, returns the minted host ids in page order.
        Raises when the store lacks room (callers check ``free_pages``
        first — the degrade-to-eviction decision is theirs)."""
        n = len(pids)
        if n > self.free_pages:
            raise ValueError(
                f"host tier full: {n} pages offered, {self.free_pages} free"
            )
        host_ids: List[int] = []
        for i, pid in enumerate(pids):
            blocks = []
            for keys, block in items:
                pax = block.ndim - 4
                blocks.append(
                    (keys, np.take(block, [i], axis=pax))
                )
            hid = self._next_id
            self._next_id += 1
            page = _HostPage(
                int(pid), blocks, _page_fingerprint(blocks),
                sum(int(b.nbytes) for _, b in blocks),
            )
            self._pages[hid] = page
            host_ids.append(hid)
            self.pages_spilled_total += 1
            self.spill_bytes_total += page.nbytes
        return tuple(host_ids)

    def get(self, host_ids: Sequence[int]):
        """Reassemble the pages at ``host_ids`` into per-leaf blocks for
        the pool's import program: ``([(path_keys, np block)], nbytes)``
        with each block's page axis of size ``len(host_ids)``, in id
        order. READ-only (the pages stay resident — the caller drops them
        once the device write is dispatched)."""
        pages = [self._require(h) for h in host_ids]
        items = []
        for j, (keys, first) in enumerate(pages[0].blocks):
            pax = first.ndim - 4
            items.append((keys, np.concatenate(
                [p.blocks[j][1] for p in pages], axis=pax
            )))
        nbytes = sum(p.nbytes for p in pages)
        self.pages_fetched_total += len(pages)
        self.fetch_bytes_total += nbytes
        return items, nbytes

    def verify(self, host_ids: Sequence[int]) -> bool:
        """Recompute every page's fingerprint against its stored bytes —
        pure host work, run before a prefetch writes anything device-side.
        False means at least one page's content no longer matches what was
        spilled: the caller must reject the WHOLE fetch (a partial rebind
        would mix good and corrupt pages into one context)."""
        ok = True
        for h in host_ids:
            page = self._pages.get(h)
            if page is None or _page_fingerprint(page.blocks) != page.fingerprint:
                ok = False
        if not ok:
            self.verify_failures_total += 1
        return ok

    def drop(self, host_ids: Sequence[int]) -> None:
        """Release pages (fetched back device-side, or their entry was
        evicted). Unknown ids are skipped — eviction races with nothing
        here, but the defensive shape matches the pool's release paths."""
        for h in host_ids:
            if self._pages.pop(h, None) is not None:
                self.pages_dropped_total += 1

    def clear(self) -> int:
        n = len(self._pages)
        self.pages_dropped_total += n
        self._pages.clear()
        return n

    # --- chaos / invariants --------------------------------------------------

    def corrupt(self, host_id: int) -> None:
        """Flip one byte of a stored page's first block IN PLACE — the
        ``poison_host_page`` chaos schedule's hand, modeling host-RAM bit
        rot. ``verify()`` must catch it."""
        page = self._require(host_id)
        keys, block = page.blocks[0]
        flat = np.ascontiguousarray(block)
        raw = bytearray(flat.tobytes())
        raw[0] ^= 0xFF
        page.blocks[0] = (
            keys,
            np.frombuffer(bytes(raw), dtype=block.dtype).reshape(block.shape),
        )

    def contains(self, host_ids: Sequence[int]) -> bool:
        return all(h in self._pages for h in host_ids)

    def check(self) -> None:
        """Host-tier half of the leak invariant: occupancy within budget,
        ids unique by construction, every page's byte count consistent
        with its blocks. AssertionError on violation."""
        assert len(self._pages) <= self.max_pages, (
            f"host tier over budget: {len(self._pages)} > {self.max_pages}"
        )
        for hid, page in self._pages.items():
            have = sum(int(b.nbytes) for _, b in page.blocks)
            assert have == page.nbytes, (
                f"host page {hid}: recorded {page.nbytes} bytes, holds {have}"
            )

    def summary(self) -> Dict[str, int]:
        """Flat scalars for halt post-mortems (depth-redaction safe)."""
        return {
            "host_pages_used": self.used_pages,
            "host_pages_max": self.max_pages,
            "host_bytes": self.nbytes,
            "host_pages_spilled_total": self.pages_spilled_total,
            "host_pages_fetched_total": self.pages_fetched_total,
            "host_pages_dropped_total": self.pages_dropped_total,
            "host_spill_bytes_total": self.spill_bytes_total,
            "host_fetch_bytes_total": self.fetch_bytes_total,
            "host_verify_failures_total": self.verify_failures_total,
        }

    def _require(self, host_id: int) -> _HostPage:
        page = self._pages.get(host_id)
        if page is None:
            raise KeyError(f"host page {host_id} is not resident")
        return page

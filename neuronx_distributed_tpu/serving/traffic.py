"""Deterministic open-loop traffic generation + replay for the engine.

Production serving is judged under realistic load — bursty, diurnal,
multi-tenant, open-loop (arrivals do not wait for completions) — not by
steady-state microbench tok/s (PAPERS.md: the Gemma-on-TPU serving
comparison reports latency-bounded throughput/goodput). This module is the
load half of the ROADMAP's SLO item: a SEEDED workload generator whose
entire arrival schedule ("tape") is materialized up front, and a replay
harness that drives a ``ServingEngine`` through it on a virtual clock, so

* the same seed yields a **byte-identical tape** (``tape_bytes`` — pinned
  by tests), and
* the same tape through the same engine configuration yields an
  **identical SLO report** (the engine's scheduling is deterministic, the
  virtual clock removes wall-time noise) — every scheduler or cache change
  is judged against reproducible load, A/B-able to the byte.

Determinism rules (graftlint GL05 enforces the spirit): every draw comes
from a per-tenant ``random.Random`` seeded from (tape seed, tenant name
CRC) — no process-global RNG, no wall clock, no dict-order dependence
(tenants are a list; the merged tape sorts by arrival time with a
deterministic tie-break).

Arrival processes:

* ``poisson`` — homogeneous Poisson at ``rate_rps`` (exponential gaps):
  the classic open-loop steady load.
* ``bursty`` — an inhomogeneous Poisson approximating diurnal traffic:
  the rate alternates between ``rate_rps`` (off-peak) and ``rate_rps *
  burst_factor`` (peak) on a ``burst_period_s`` cycle with ``burst_duty``
  of each cycle at peak, sampled by thinning against the peak rate. Same
  mean-ish load as poisson, radically worse tail behavior — the shape
  that actually breaks SLOs.

Workload shapes: ``chat`` (short prompt, short generation) and
``longdoc`` (long prompt, longer generation) — the two-population mix
whose interference the prefix cache, paging, and the future SLO scheduler
all care about.

Hot-path contract (this module is on graftlint GL02's hot-path list — the
replay loop wraps ``engine.step()``): nothing here reads a device value;
the engine's own pinned sync budget is the only device→host traffic.
"""

from __future__ import annotations

import dataclasses
import json
import random
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from neuronx_distributed_tpu.inference.generate import GenerationConfig

__all__ = [
    "Arrival",
    "TenantProfile",
    "VirtualClock",
    "generate_tape",
    "tape_bytes",
    "replay",
    "build_report",
]

# (prompt_lo, prompt_hi, new_lo, new_hi) — inclusive token-count ranges
_WORKLOADS: Dict[str, Tuple[int, int, int, int]] = {
    "chat": (4, 16, 8, 20),
    "longdoc": (24, 48, 16, 32),
}


@dataclasses.dataclass(frozen=True)
class TenantProfile:
    """One tenant's traffic shape. ``rate_rps`` is the OFF-PEAK rate for
    ``bursty`` arrivals (peak = ``rate_rps * burst_factor``)."""

    name: str
    rate_rps: float = 1.0
    arrival: str = "poisson"  # "poisson" | "bursty"
    burst_factor: float = 4.0
    burst_period_s: float = 8.0
    burst_duty: float = 0.25
    workload: str = "chat"  # "chat" | "longdoc"
    priority: str = "standard"
    temperature: float = 0.0
    deadline_s: Optional[float] = None
    queue_timeout_s: Optional[float] = None

    def __post_init__(self):
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.arrival not in ("poisson", "bursty"):
            raise ValueError(f"unknown arrival process {self.arrival!r}")
        if self.workload not in _WORKLOADS:
            raise ValueError(
                f"unknown workload {self.workload!r} "
                f"(have {sorted(_WORKLOADS)})"
            )
        if self.arrival == "bursty":
            if self.burst_factor < 1.0:
                raise ValueError("burst_factor must be >= 1")
            if not 0.0 < self.burst_duty < 1.0:
                raise ValueError("burst_duty must be in (0, 1)")
            if self.burst_period_s <= 0:
                raise ValueError("burst_period_s must be > 0")


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One tape entry: everything ``replay`` needs to submit the request
    (prompt ids inline — the tape fully determines the workload)."""

    t: float
    tenant: str
    priority: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    temperature: float
    key_seed: int
    deadline_s: Optional[float] = None
    queue_timeout_s: Optional[float] = None


def _tenant_seed(seed: int, name: str) -> int:
    """Stable per-tenant stream seed: NEVER ``hash()`` (salted per
    process — it would silently break cross-run byte-identity)."""
    return (int(seed) * 1_000_003 + zlib.crc32(name.encode("utf-8"))) \
        & 0x7FFFFFFF


def _burst_rate(tp: TenantProfile, t: float) -> float:
    """Instantaneous rate of the bursty (square-wave diurnal) process."""
    phase = (t % tp.burst_period_s) / tp.burst_period_s
    if phase < tp.burst_duty:
        return tp.rate_rps * tp.burst_factor
    return tp.rate_rps


def generate_tape(
    tenants: Sequence[TenantProfile],
    duration_s: float,
    seed: int = 0,
    vocab_size: int = 32000,
) -> List[Arrival]:
    """Materialize the full arrival schedule for ``duration_s`` virtual
    seconds. Same (tenants, duration, seed, vocab) ⇒ byte-identical tape
    (:func:`tape_bytes`); per-tenant streams are independent, so adding a
    tenant never perturbs another's arrivals."""
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    if len({tp.name for tp in tenants}) != len(tuple(tenants)):
        raise ValueError("tenant names must be unique")
    arrivals: List[Arrival] = []
    for tp in tenants:
        rng = random.Random(_tenant_seed(seed, tp.name))
        p_lo, p_hi, n_lo, n_hi = _WORKLOADS[tp.workload]
        peak = (
            tp.rate_rps * tp.burst_factor if tp.arrival == "bursty"
            else tp.rate_rps
        )
        t = 0.0
        while True:
            # draw at the peak rate, then thin to the instantaneous rate —
            # the standard inhomogeneous-Poisson construction (exact, and
            # one uniform per candidate keeps the stream deterministic)
            t += rng.expovariate(peak)
            if t >= duration_s:
                break
            if tp.arrival == "bursty" and (
                rng.random() > _burst_rate(tp, t) / peak
            ):
                continue
            plen = rng.randint(p_lo, p_hi)
            prompt = tuple(
                rng.randint(1, vocab_size - 1) for _ in range(plen)
            )
            arrivals.append(
                Arrival(
                    t=t,
                    tenant=tp.name,
                    priority=tp.priority,
                    prompt=prompt,
                    max_new_tokens=rng.randint(n_lo, n_hi),
                    temperature=tp.temperature,
                    key_seed=rng.getrandbits(31),
                    deadline_s=tp.deadline_s,
                    queue_timeout_s=tp.queue_timeout_s,
                )
            )
    # merge the per-tenant streams; the (tenant, key_seed) tie-break makes
    # the order total and deterministic even for (improbable) equal times
    arrivals.sort(key=lambda a: (a.t, a.tenant, a.key_seed))
    return arrivals


def tape_bytes(tape: Sequence[Arrival]) -> bytes:
    """Canonical byte serialization of a tape — the determinism pin
    (``repr`` of Python floats is shortest-round-trip, so equal floats
    serialize equally and unequal ones never collide)."""
    return json.dumps(
        [dataclasses.asdict(a) for a in tape],
        sort_keys=True, separators=(",", ":"),
    ).encode("utf-8")


class VirtualClock:
    """Injectable engine clock (``ServingEngine(time_fn=clock)``) owned by
    the replay loop: virtual time advances a fixed amount per engine step
    and jumps across idle gaps, so every latency the metrics layer
    measures is a deterministic function of the schedule — wall-clock
    noise (and therefore run-to-run drift) cannot exist."""

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt

    def advance_to(self, t: float) -> None:
        if t > self.now:
            self.now = t


def replay(
    engine,
    tape: Sequence[Arrival],
    clock: VirtualClock,
    step_dt: float = 0.01,
    max_steps: int = 200_000,
) -> dict:
    """Drive ``engine`` (built with ``time_fn=clock``) through ``tape``
    open-loop: every arrival is submitted at its scheduled virtual time
    whatever the engine's state (a full queue REJECTS, a permanently-
    unplaceable request fails at the door — both are attributed signal,
    not replay errors), each ``engine.step()`` costs ``step_dt`` virtual
    seconds, and idle gaps fast-forward. Returns :func:`build_report`'s
    SLO report plus replay bookkeeping."""
    if step_dt <= 0:
        raise ValueError(f"step_dt must be > 0, got {step_dt}")
    clk = getattr(engine, "_clock", None)
    if clk is not clock:
        raise ValueError(
            "engine must be constructed with time_fn=<this VirtualClock> "
            "— replaying against a wall-clock engine would measure noise"
        )
    from neuronx_distributed_tpu.serving.engine import RejectedError

    submitted = 0
    rejected = 0
    unplaceable = 0
    steps = 0
    i = 0
    while i < len(tape) or engine.has_work:
        while i < len(tape) and tape[i].t <= clock.now:
            a = tape[i]
            i += 1
            cfg = GenerationConfig(
                max_new_tokens=a.max_new_tokens,
                temperature=a.temperature,
                eos_token_id=None,
            )
            try:
                engine.submit(
                    np.asarray(a.prompt, np.int32), cfg,
                    key=_replay_key(a.key_seed),
                    tenant=a.tenant, priority=a.priority,
                    deadline_s=a.deadline_s,
                    queue_timeout_s=a.queue_timeout_s,
                )
                submitted += 1
            except RejectedError:
                rejected += 1  # attributed in metrics/SLO by the engine
            except ValueError as e:
                # a PERMANENTLY-unplaceable arrival (seq-len class, token
                # budget, page footprint vs an undersized engine): the
                # engine fails it at the door BEFORE any metrics record.
                # One impossible request must not cost the whole replay
                # its report — attribute it as a reject (it is one, with
                # a reason) and keep going; the count below is the signal
                # that the engine is undersized for the tape
                unplaceable += 1
                engine.metrics.record_reject(
                    engine.scheduler.queued, f"unplaceable: {e}",
                    tenant=a.tenant, now=clock.now,
                )
        if not engine.has_work:
            if i < len(tape):
                clock.advance_to(tape[i].t)
                continue
            break
        if steps >= max_steps:
            break
        engine.step()
        steps += 1
        clock.advance(step_dt)
    report = build_report(engine)
    report["replay"] = {
        "arrivals": len(tape),
        "submitted": submitted,
        "rejected": rejected,
        "unplaceable": unplaceable,
        "steps": steps,
        "step_dt_s": step_dt,
        "virtual_end_s": clock.now,
        "truncated": steps >= max_steps,
    }
    return report


def _replay_key(seed: int):
    import jax

    return jax.random.PRNGKey(seed)


def build_report(engine) -> dict:
    """The SLO report: per-tenant p50/p99 TTFT & TPOT, goodput,
    attainment, and shed/timeout/reject attribution — tenant-sorted, so
    two identical runs serialize to identical JSON."""
    snap = engine.metrics.snapshot()
    slo = snap.get("slo")
    tenants = {}
    for tenant, row in snap.get("tenants", {}).items():
        entry = {
            "submitted": row["submitted"],
            "completed": row["completed"],
            "tokens": row["decode_tokens"],
            "sheds": row["sheds"],
            "timed_out": row["timed_out"],
            "rejects": row["rejects"],
            "failed": row["failed"],
            "ttft_p50_s": row["ttft_p50_s"],
            "ttft_p99_s": row["ttft_p99_s"],
            "tpot_p50_s": row["tpot_p50_s"],
            "tpot_p99_s": row["tpot_p99_s"],
            "queue_wait_p95_s": row["queue_wait_p95_s"],
        }
        if slo is not None:
            t_slo = slo["per_tenant"].get(tenant)
            if t_slo is not None:
                entry.update(
                    attained=t_slo["attained"],
                    violated=t_slo["violated"],
                    attainment=t_slo["attainment"],
                    goodput_tok_s=t_slo["goodput_tok_s"],
                )
        tenants[tenant] = entry
    report = {
        "tenants": tenants,
        "completed": snap["completed"],
        "sheds": snap["sheds"],
        "timed_out": snap["timed_out"],
        "rejects": snap["rejects"],
        "decode_tokens": snap["decode_tokens"],
        "preemptions": snap["preemptions"],
        "health": snap["health"],
    }
    if slo is not None:
        report["slo"] = {
            "attained": slo["attained"],
            "violated": slo["violated"],
            "attainment": slo["attainment"],
            "goodput_tok_s": slo["goodput_tok_s"],
            "span_s": slo["span_s"],
            "violation_reasons": slo["violation_reasons"],
        }
    return report

"""The scheduling-policy seam: ONE selection path, pluggable order.

ISSUE 16 tentpole. Before this package, the admission selection logic
lived in two places: ``Scheduler.select`` owned the budget-guarded queue
scan plus the longest-prefill-first round ordering, while the engine's
``_admit`` built the capacity predicate and the effective-prefill-cost
key around it. The scan and the ordering now live HERE — :func:`scan_queue`
and :func:`order_round` are the single selection path every policy rides —
and ``Scheduler.select`` is a thin delegate to its bound policy.

A :class:`SchedulingPolicy` decides three things, all host-side over
already-host state (the hard constraint: ZERO added device→host syncs —
these modules are on graftlint GL02's hot list):

* **queue order** — :meth:`SchedulingPolicy.select` may reorder the live
  queue before the scan (the scan itself never overtakes: the first
  request that does not fit blocks the rest, which is what makes the
  budget guard starvation-free *within the policy's order*);
* **preemption** — :meth:`SchedulingPolicy.victims` nominates active
  requests to vacate (the engine preempts them through the existing
  resume machinery, so streams stay bit-identical);
* **routing bias** — :meth:`SchedulingPolicy.route_bias` feeds the
  replica router's per-tenant attainment term.

:class:`FifoPolicy` is the default and reproduces the pre-policy
``Scheduler.select`` decision-for-decision: same scan, same ordering,
no reorder, no victims, zero bias — streams are bit-identical to the
pre-policy engine (regression-pinned in tests/serving/test_sched_policy.py
and the whole existing serving test matrix, which runs through it).
"""

from __future__ import annotations

from typing import Callable, Deque, List, Optional, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from neuronx_distributed_tpu.serving.scheduler import Request


def scan_queue(
    queue: "Deque[Request]",
    free_slots: int,
    in_flight_tokens: int,
    max_tokens_in_flight: Optional[int],
    fits: Optional[Callable[["Request"], bool]] = None,
) -> List["Request"]:
    """THE selection scan (moved verbatim from ``Scheduler.select``): pick
    the queue-order prefix that fits ``free_slots``, the token budget, and
    the engine's capacity predicate ``fits`` (checked in queue order, so
    ``fits`` may accumulate a projected cursor). Selected requests leave
    the queue in state PREFILL. Strict no-overtaking: the first request
    that does not fit blocks everything behind it — under FIFO that is the
    classic head-of-line guarantee; under a reordering policy it means the
    policy's chosen head is never starved by smaller work behind it."""
    from neuronx_distributed_tpu.serving.scheduler import RequestState

    selected: List["Request"] = []
    budget = in_flight_tokens
    while queue and len(selected) < free_slots:
        req = queue[0]
        if req.finished:  # cancelled/shed while queued — drop in place
            queue.popleft()
            continue
        if (
            max_tokens_in_flight is not None
            and budget + req.token_footprint > max_tokens_in_flight
        ):
            break  # nothing overtakes the blocked head
        if fits is not None and not fits(req):
            break
        queue.popleft()
        req.state = RequestState.PREFILL
        budget += req.token_footprint
        selected.append(req)
    return selected


def order_round(
    selected: List["Request"],
    prefill_cost: Optional[Callable[["Request"], int]] = None,
) -> List["Request"]:
    """THE round ordering (the other half moved from ``Scheduler.select``):
    hand the selected round back longest-prefill-first — the longest
    prompt sets the shared cache cursor, so prefilling it first lets the
    shorter prompts roll in under the same cursor without gap columns.
    ``prefill_cost`` substitutes EFFECTIVE work (the prefix-cache-aware
    engine passes context length minus reusable tokens). Ordering only —
    selection already happened, so token streams are unaffected."""
    key = prefill_cost or (lambda r: len(r.context_ids))
    selected.sort(key=key, reverse=True)
    return selected


class SchedulingPolicy:
    """Interface every queue policy implements. Stateless against device
    data by construction: every hook takes and returns host scalars."""

    name = "base"

    def bind(self, engine) -> None:
        """Late wiring to the engine whose queue this policy orders (the
        SLO policy reads its metrics/prefix/cache feedback surfaces; FIFO
        ignores it). Called once from ``ServingEngine.__init__``."""

    def select(
        self,
        queue: "Deque[Request]",
        free_slots: int,
        in_flight_tokens: int,
        max_tokens_in_flight: Optional[int],
        fits: Optional[Callable[["Request"], bool]] = None,
        prefill_cost: Optional[Callable[["Request"], int]] = None,
        now: Optional[float] = None,
    ) -> List["Request"]:
        raise NotImplementedError

    def victims(self, now: float) -> List["Request"]:
        """Active requests this policy wants preempted RIGHT NOW (the
        engine vacates them through the resume machinery — tokens and key
        host-current, streams bit-identical). Default: never."""
        return []

    def on_tokens(self, tenant: str, n: int) -> None:
        """Decode-token accounting hook (host ints the loop already owns);
        the fairness layer charges tenant budgets here."""

    def route_bias(self, tenant: Optional[str]) -> float:
        """Additive per-tenant load-score term for the replica router, in
        slot units (>= 0; 0.0 = no opinion). A replica where ``tenant``'s
        SLO is unhealthy reads as more loaded for that tenant's work."""
        return 0.0

    def snapshot(self) -> dict:
        return {"policy": self.name}


class FifoPolicy(SchedulingPolicy):
    """The pre-policy scheduler, verbatim: FIFO scan + longest-prefill-
    first round ordering, no reordering, no preemption, no routing bias.
    Selecting this policy IS the pre-PR engine (bit-identical streams)."""

    name = "fifo"

    def select(self, queue, free_slots, in_flight_tokens,
               max_tokens_in_flight, fits=None, prefill_cost=None,
               now=None):
        selected = scan_queue(
            queue, free_slots, in_flight_tokens, max_tokens_in_flight, fits
        )
        return order_round(selected, prefill_cost)


def make_policy(spec) -> SchedulingPolicy:
    """``ServingEngine(scheduling=)`` resolver: ``"fifo"`` (default),
    ``"slo"`` (the ISSUE 16 SLO-aware policy with priority tiers, DWRR
    fairness, and attainment feedback), or a ready
    :class:`SchedulingPolicy` instance."""
    if isinstance(spec, SchedulingPolicy):
        return spec
    if spec in (None, "fifo"):
        return FifoPolicy()
    if spec == "slo":
        from neuronx_distributed_tpu.serving.sched.feedback import SloPolicy

        return SloPolicy()
    raise ValueError(
        f"unknown scheduling policy {spec!r} (expected 'fifo', 'slo', or a "
        "SchedulingPolicy instance)"
    )

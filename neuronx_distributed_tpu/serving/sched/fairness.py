"""Per-tenant token-rate fairness: deficit-weighted round-robin (ISSUE 16
tentpole (b)).

Slots are the engine's scarce resource, but TOKENS are what tenants
consume them in — one longdoc tenant's burst of 400-token generations can
monopolize every slot while a chat tenant's 12-token turns queue behind
it, even though the chat tenant is asking for a fraction of the
throughput. Classic deficit round-robin (Shreedhar & Varghese), adapted
to decode token budgets:

* every admission round, each tenant WITH QUEUED WORK earns
  ``quantum_tokens x weight(tenant)`` of deficit (weights default to the
  priority-tier ladder, so an interactive tenant earns credit faster than
  batch);
* every decode token a tenant's requests emit is CHARGED against its
  deficit (the engine's ``_emit_token`` hook — host ints, zero syncs);
* the scheduler prefers tenants with the largest deficit — the ones
  furthest below their earned token rate.

Deficits are clamped to ``[-burst_tokens, burst_tokens]``: the cap keeps
an idle tenant from banking unbounded credit and then starving everyone
on return (the standard DRR idle-flush, softened to a burst allowance so
a briefly-quiet chat tenant still gets its latency-friendly head start),
and the floor keeps one saturated tenant's debt from overflowing into
permanent last place once its competitors drain.

GL02-hot module: pure host arithmetic over dict counters.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional

from neuronx_distributed_tpu.serving.sched.priority import tier_rank

# weight per priority tier rank (0 = realtime ... 3 = batch): one tier up
# doubles the earned token rate
_TIER_WEIGHTS = (8.0, 4.0, 2.0, 1.0)


def tier_weight(priority: Optional[str]) -> float:
    return _TIER_WEIGHTS[tier_rank(priority)]


@dataclasses.dataclass(frozen=True)
class FairnessConfig:
    """``quantum_tokens`` is the per-round earn rate for a weight-1.0
    (batch) tenant — sized to a typical decode chunk so one round's credit
    is about one slot-chunk of work. ``burst_tokens`` bounds banked credit
    and debt."""

    quantum_tokens: int = 32
    burst_tokens: int = 512

    def __post_init__(self):
        if self.quantum_tokens < 1:
            raise ValueError(
                f"quantum_tokens must be >= 1, got {self.quantum_tokens}"
            )
        if self.burst_tokens < self.quantum_tokens:
            raise ValueError(
                "burst_tokens must be >= quantum_tokens "
                f"({self.burst_tokens} < {self.quantum_tokens})"
            )


class DeficitRoundRobin:
    """Per-tenant deficit counters over decode token budgets."""

    def __init__(self, config: Optional[FairnessConfig] = None,
                 weight=tier_weight):
        self.config = config or FairnessConfig()
        self._weight = weight
        self._deficit: Dict[str, float] = {}
        self.tokens_charged = 0  # lifetime accounting (snapshot only)

    def replenish(self, queued: Iterable[tuple]) -> None:
        """One admission round's credit: each ``(tenant, priority)`` pair
        with queued work earns ``quantum x weight``. Tenants with nothing
        queued earn nothing (credit accrues toward WAITING work, not in
        absentia — the burst clamp would cap it anyway, but this keeps the
        counters honest for the snapshot)."""
        cap = float(self.config.burst_tokens)
        for tenant, priority in queued:
            d = self._deficit.get(tenant, 0.0)
            d += self.config.quantum_tokens * self._weight(priority)
            self._deficit[tenant] = min(d, cap)

    def charge(self, tenant: str, tokens: int) -> None:
        """Decode tokens consumed by ``tenant`` — spend its deficit."""
        floor = -float(self.config.burst_tokens)
        d = self._deficit.get(tenant, 0.0) - tokens
        self._deficit[tenant] = max(d, floor)
        self.tokens_charged += tokens

    def deficit(self, tenant: str) -> float:
        return self._deficit.get(tenant, 0.0)

    def rank(self, tenant: str) -> float:
        """Ordering component: NEGATIVE normalized deficit (most-starved
        tenant sorts first). Normalized by the burst cap so the value is
        in [-1, 1] and composes with the priority ladder on a known
        scale."""
        return -self.deficit(tenant) / float(self.config.burst_tokens)

    def snapshot(self) -> dict:
        return {
            "deficits": {
                t: round(d, 3) for t, d in sorted(self._deficit.items())
            },
            "tokens_charged": self.tokens_charged,
        }

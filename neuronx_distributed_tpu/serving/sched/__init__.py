"""SLO-aware multi-tenant scheduling subsystem (ISSUE 16).

The FIFO queue behind :class:`~neuronx_distributed_tpu.serving.scheduler.
Scheduler` is now a POLICY: ``ServingEngine(scheduling=)`` selects
``"fifo"`` (the default — decision-for-decision the pre-policy scheduler,
bit-identical streams), ``"slo"`` (priority tiers with starvation-free
aging, deficit-weighted round-robin token fairness, and feedback-driven
admission/preemption off the live SLO surfaces), or any
:class:`SchedulingPolicy` instance.

Modules:

* :mod:`.policy` — the interface, the ONE selection scan + round ordering
  every policy shares, and :class:`FifoPolicy`.
* :mod:`.priority` — strict tiers (realtime > interactive > standard >
  batch) with a continuous aging discount so nothing starves.
* :mod:`.fairness` — :class:`DeficitRoundRobin` over decode token
  budgets: one tenant's longdoc burst cannot monopolize slots.
* :mod:`.feedback` — :class:`SloPolicy`: attainment-pressure admission
  boost, cheapest-victim preemption (pages held x resume-prefill work),
  and the router's per-tenant attainment bias.

Everything is host-side over already-host state — zero added device→host
syncs (graftlint GL02 lists all four modules as hot; budgets re-pinned in
tests/serving/test_host_sync.py with the SLO policy ON).
"""

from neuronx_distributed_tpu.serving.sched.fairness import (
    DeficitRoundRobin,
    FairnessConfig,
    tier_weight,
)
from neuronx_distributed_tpu.serving.sched.feedback import (
    FeedbackConfig,
    SloFeedback,
    SloPolicy,
    victim_cost,
)
from neuronx_distributed_tpu.serving.sched.policy import (
    FifoPolicy,
    SchedulingPolicy,
    make_policy,
    order_round,
    scan_queue,
)
from neuronx_distributed_tpu.serving.sched.priority import (
    TIER_RANK,
    PriorityConfig,
    effective_rank,
    tier_rank,
)

__all__ = [
    "DeficitRoundRobin",
    "FairnessConfig",
    "FeedbackConfig",
    "FifoPolicy",
    "PriorityConfig",
    "SchedulingPolicy",
    "SloFeedback",
    "SloPolicy",
    "TIER_RANK",
    "effective_rank",
    "make_policy",
    "order_round",
    "scan_queue",
    "tier_rank",
    "tier_weight",
    "victim_cost",
]

"""Priority classes with starvation-free aging (ISSUE 16 tentpole (a)).

``Request.priority`` has threaded through the stack since ISSUE 11 as an
attribution string; this module makes it MEAN something. Four strict
tiers (rank 0 admits first):

    realtime (0) > interactive (1) > standard (2) > batch (3)

Unknown strings map to ``standard`` — the tiers are a contract with the
traffic layer's ``TenantProfile.priority``, not an enum, so a tenant label
like ``"bulk-reindex"`` degrades gracefully instead of raising mid-admit.

Strict tiers starve: one saturated interactive tenant would pin batch work
in the queue forever. Aging fixes that with a time-derived rank DISCOUNT:
a queued request's effective rank drops by one tier per ``aging_s``
seconds of queue wait, so any request eventually outranks fresh top-tier
arrivals — the classic aging ladder, continuous rather than stepped so
two batch requests submitted 1ms apart never flap order. All host float
arithmetic over timestamps the request already carries (GL02-hot module:
nothing here may touch device values).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from neuronx_distributed_tpu.serving.scheduler import Request

# strict tier ranks — lower admits first
TIER_RANK = {
    "realtime": 0,
    "interactive": 1,
    "standard": 2,
    "batch": 3,
}
_DEFAULT_RANK = TIER_RANK["standard"]


def tier_rank(priority: Optional[str]) -> int:
    """Rank of a priority string; unknown labels are ``standard``."""
    return TIER_RANK.get(priority or "standard", _DEFAULT_RANK)


@dataclasses.dataclass(frozen=True)
class PriorityConfig:
    """Aging dial: ``aging_s`` seconds of queue wait promote a request by
    one tier. The default (2s) is sized to the serving SLOs this repo
    ships (ttft p99 bounds are 0.1–1s): batch work waits at most
    ``3 * aging_s`` before it outranks everything, bounding its queue
    delay without letting it flap ahead of a live interactive burst."""

    aging_s: float = 2.0

    def __post_init__(self):
        if self.aging_s <= 0:
            raise ValueError(f"aging_s must be > 0, got {self.aging_s}")


def effective_rank(req: "Request", now: float,
                   config: PriorityConfig) -> float:
    """Tier rank minus the aging discount — the priority component of the
    SLO policy's ordering key. Monotonically decreasing in queue wait, so
    no request waits forever behind a higher tier. Preempted requests age
    from their ORIGINAL submit time: a victim resumes with its accumulated
    seniority intact (it was admitted once already — re-queuing must not
    demote it behind the arrivals it beat the first time)."""
    waited = 0.0
    if req.submit_time is not None and now > req.submit_time:
        waited = now - req.submit_time
    return tier_rank(req.priority) - waited / config.aging_s

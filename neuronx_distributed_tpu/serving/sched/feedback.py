"""Feedback-driven SLO scheduling (ISSUE 16 tentpole (c)+(d)): the policy
that closes the loop from the PR-10 judge back into admission.

PR 10/11 built the measurement half — per-tenant ``ttft_*``/``tpot_*``
histograms and :class:`SLOTracker` attainment — and left the scheduler
FIFO. :class:`SloPolicy` reads those live host-side counters as its
CONTROL SIGNAL:

* **ordering** — the queue is stably reordered each admission round by a
  composite key: priority tier with starvation-free aging
  (:mod:`.priority`), the DWRR fairness rank (:mod:`.fairness`), and an
  attainment-pressure boost — a tenant whose attainment has dipped below
  target (or whose LIVE ttft p99 is already over its spec, the
  early-warning the finish-time tracker can't see mid-burst) admits ahead
  of over-attaining tenants. The scan itself is the shared single path in
  :mod:`.policy` (same budget guard, same no-overtaking).
* **preemption** — when the slot set is full and a pressured tenant's
  work is waiting behind a healthy tenant's, :meth:`SloPolicy.victims`
  nominates the cheapest victim: cost = pages held x resume-prefill work
  (``PrefixCache.match_len`` makes the resume work cheap to estimate —
  a victim whose context is prefix-cached re-prefills almost nothing).
  The engine vacates victims through the existing preempt/resume
  machinery, so the victim's stream is bit-identical; a cooldown and a
  per-round victim cap keep the controller from thrashing.
* **routing** — :meth:`SloPolicy.route_bias` exposes per-tenant pressure
  in slot units for ``ServingEngine.load_score(tenant=)``: the router
  steers a tenant's next request toward the replica where its SLO is
  healthiest.

Every input is host state the loop already owns (tracker counters,
log-bucketed histogram reads, host block tables): ZERO added device→host
syncs, re-pinned with this policy ON in tests/serving/test_host_sync.py.
GL02-hot module.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, TYPE_CHECKING

from neuronx_distributed_tpu.serving.sched.fairness import (
    DeficitRoundRobin,
    FairnessConfig,
)
from neuronx_distributed_tpu.serving.sched.policy import (
    SchedulingPolicy,
    order_round,
    scan_queue,
)
from neuronx_distributed_tpu.serving.sched.priority import (
    PriorityConfig,
    effective_rank,
)

if TYPE_CHECKING:  # pragma: no cover
    from neuronx_distributed_tpu.serving.scheduler import Request


@dataclasses.dataclass(frozen=True)
class FeedbackConfig:
    """Controller dials. ``target_attainment`` is the floor below which a
    tenant counts as pressured; ``min_decided`` gates the attainment term
    until enough requests have been classified to mean anything (the live
    ttft early-warning has no such gate — one bad burst is signal).
    ``pressure_boost`` converts pressure (0..1) into tier units: the
    default (2.0 tiers at full pressure) lets a fully-failing batch tenant
    overtake healthy interactive work, but a mildly-dipping one only edges
    ahead of its own tier. Preemption is deliberately conservative:
    at most ``max_victims`` per round, never from a pressured tenant,
    never within ``cooldown_s`` of the last preemption (the resume
    re-prefill costs real work — thrash would burn more SLO than it
    buys)."""

    target_attainment: float = 0.9
    min_decided: int = 4
    pressure_boost: float = 2.0
    fairness_gain: float = 1.0
    preempt: bool = True
    max_victims: int = 1
    cooldown_s: float = 0.25
    # don't victimize a request about to finish — its slot frees itself
    # cheaper than a preempt/resume cycle can
    min_victim_remaining: int = 4


class SloFeedback:
    """Host-side reads over the live SLO surfaces: tracker attainment +
    histogram percentiles, normalized to a 0..1 pressure per tenant."""

    def __init__(self, metrics, config: FeedbackConfig):
        self._metrics = metrics
        self._config = config

    def pressure(self, tenant: str) -> float:
        """0.0 = attaining (or no spec / no signal yet); 1.0 = fully
        failing. Max of the attainment gap (normalized to the target) and
        the live-ttft overshoot early-warning."""
        tracker = self._metrics.slo
        if tracker is None:
            return 0.0
        spec = tracker.spec_for(tenant)
        if spec is None:
            return 0.0  # no contract, no pressure
        cfg = self._config
        p = 0.0
        if tracker.decided(tenant) >= cfg.min_decided:
            gap = cfg.target_attainment - tracker.attainment(tenant)
            if gap > 0.0:
                p = min(1.0, gap / max(cfg.target_attainment, 1e-9))
        if spec.ttft_p99_s is not None:
            live = self._metrics.tenant_latency("ttft", tenant, 0.99)
            if live > spec.ttft_p99_s:
                p = max(p, min(1.0, live / spec.ttft_p99_s - 1.0))
        return p

    def attaining(self, tenant: str) -> bool:
        return self.pressure(tenant) == 0.0


def victim_cost(engine, req: "Request") -> float:
    """What preempting ``req`` throws away: pages held x the prefill work
    its resume must redo. ``PrefixCache.match_len`` is a read-only peek
    (no LRU state moves), and a victim admitted through the prefix cache
    usually re-prefills only its generated tail — cheap. Row engines hold
    no pages; their cost is pure resume work."""
    ctx = req.context_ids
    work = len(ctx)
    if engine.prefix is not None:
        work -= engine.prefix.match_len(ctx)
    work = max(work, 1)
    pages = 1
    if engine._page_size is not None and req.slot is not None:
        pages = max(len(engine.cache.slot_pages(req.slot)), 1)
    return float(pages * work)


class SloPolicy(SchedulingPolicy):
    """Priority tiers + DWRR fairness + attainment feedback, composed over
    the shared selection path."""

    name = "slo"

    def __init__(
        self,
        priority: Optional[PriorityConfig] = None,
        fairness: Optional[FairnessConfig] = None,
        feedback: Optional[FeedbackConfig] = None,
    ):
        self.priority = priority or PriorityConfig()
        self.fairness = DeficitRoundRobin(fairness or FairnessConfig())
        self.config = feedback or FeedbackConfig()
        self._engine = None
        self._feedback: Optional[SloFeedback] = None
        self._last_preempt_t: Optional[float] = None
        self.preemptions_requested = 0

    # --- wiring -------------------------------------------------------------

    def bind(self, engine) -> None:
        self._engine = engine
        self._feedback = SloFeedback(engine.metrics, self.config)

    # --- ordering -----------------------------------------------------------

    def _order_key(self, req: "Request", now: float):
        rank = effective_rank(req, now, self.priority)
        rank += self.fairness.rank(req.tenant) * self.config.fairness_gain
        if self._feedback is not None:
            rank -= (
                self._feedback.pressure(req.tenant)
                * self.config.pressure_boost
            )
        return (rank, req.rid)  # rid tiebreak: stable arrival order

    def select(self, queue, free_slots, in_flight_tokens,
               max_tokens_in_flight, fits=None, prefill_cost=None,
               now=None):
        now = 0.0 if now is None else now
        live = {}
        for r in queue:
            if not r.finished:
                live.setdefault(r.tenant, r.priority)
        self.fairness.replenish(live.items())
        if live:
            # stable in-place reorder of the scheduler's own deque: the
            # policy's order IS the queue order (requeued victims keep
            # their aged seniority through the key, not their position)
            ordered = sorted(queue, key=lambda r: self._order_key(r, now))
            queue.clear()
            queue.extend(ordered)
        selected = scan_queue(
            queue, free_slots, in_flight_tokens, max_tokens_in_flight, fits
        )
        # charge admissions' context work? No — fairness meters DECODE
        # tokens only (the engine's on_tokens hook); prefill cost is
        # already priced by the longest-first round order below
        return order_round(selected, prefill_cost)

    # --- preemption ---------------------------------------------------------

    def victims(self, now: float) -> List["Request"]:
        cfg = self.config
        eng = self._engine
        if not cfg.preempt or eng is None or self._feedback is None:
            return []
        if eng.cache.free_slots > 0:
            return []  # admission can proceed without violence
        if (
            self._last_preempt_t is not None
            and now - self._last_preempt_t < cfg.cooldown_s
        ):
            return []
        # who is waiting and hurting?
        pressured = {}
        for req in eng.scheduler.queued_requests:
            p = self._feedback.pressure(req.tenant)
            if p > 0.0:
                pressured[req.tenant] = max(pressured.get(req.tenant, 0.0), p)
        if not pressured:
            return []
        # who can pay? active requests of ATTAINING tenants only, with
        # enough work left that a preempt/resume cycle beats waiting out
        # their natural retirement
        candidates = [
            r for r in eng._slot_req
            if r is not None
            and not r.finished
            and r.tenant not in pressured
            and self._feedback.attaining(r.tenant)
            and r.remaining_new_tokens >= cfg.min_victim_remaining
        ]
        if not candidates:
            return []
        candidates.sort(key=lambda r: (victim_cost(eng, r), r.rid))
        chosen = candidates[: cfg.max_victims]
        self._last_preempt_t = now
        self.preemptions_requested += len(chosen)
        return chosen

    # --- accounting / routing ----------------------------------------------

    def on_tokens(self, tenant: str, n: int) -> None:
        self.fairness.charge(tenant, n)

    def route_bias(self, tenant: Optional[str]) -> float:
        if tenant is None or self._feedback is None:
            return 0.0
        return self._feedback.pressure(tenant)

    def snapshot(self) -> dict:
        return {
            "policy": self.name,
            "preemptions_requested": self.preemptions_requested,
            "fairness": self.fairness.snapshot(),
        }

"""Request-level serving engine: continuous batching over a fixed-shape
decode step (ROADMAP north star — serving heavy traffic needs an engine that
admits/retires REQUESTS, not a batch `generate()` call; cf. the TPU serving
stacks in PAPERS.md, which all converge on slot-based continuous batching so
XLA compiles the decode step once and requests flow through slots).

Layers:

* :mod:`engine` — ``ServingEngine``: the host-side loop interleaving prefill
  of admitted requests with ONE jitted fixed-shape decode program over all
  active slots — ``decode_chunk_size`` fused steps per dispatch, donated
  device-resident cache/slot-state, one host sync per chunk.
* :mod:`scheduler` — FIFO + longest-prefill-first admission with a
  token-budget guard and the request lifecycle
  (QUEUED→PREFILL→DECODE→DONE/CANCELLED).
* :mod:`cache_manager` — slot allocation/roll-in/reset on top of the
  ``modules/attention.KVCache`` collection layout (no reallocation between
  requests).
* :mod:`metrics` — TTFT / decode throughput / queue wait / occupancy /
  preemption counters, exported as a plain dict snapshot and (optionally)
  onto a ``utils.timeline.Timeline``.
"""

from neuronx_distributed_tpu.serving.cache_manager import SlotCacheManager
from neuronx_distributed_tpu.serving.engine import ServingEngine
from neuronx_distributed_tpu.serving.metrics import ServingMetrics
from neuronx_distributed_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
)

__all__ = [
    "Request",
    "RequestState",
    "Scheduler",
    "ServingEngine",
    "ServingMetrics",
    "SlotCacheManager",
]

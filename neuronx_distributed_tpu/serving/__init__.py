"""Request-level serving engine: continuous batching over a fixed-shape
decode step (ROADMAP north star — serving heavy traffic needs an engine that
admits/retires REQUESTS, not a batch `generate()` call; cf. the TPU serving
stacks in PAPERS.md, which all converge on slot-based continuous batching so
XLA compiles the decode step once and requests flow through slots).

Layers:

* :mod:`engine` — ``ServingEngine``: the host-side loop interleaving prefill
  of admitted requests with ONE jitted fixed-shape decode program over all
  active slots — ``decode_chunk_size`` fused steps per dispatch, donated
  device-resident cache/slot-state, one host sync per chunk. With
  ``draft_model=`` bound, each chunk becomes that many fused draft–verify
  ROUNDS (speculative decoding, ISSUE 9): 1..gamma tokens per slot per
  round with per-slot variable advance, greedy streams bit-identical to
  the spec-off engine and the solo speculative path, still one sync per
  chunk.
* :mod:`scheduler` — FIFO + longest-prefill-first admission with a
  token-budget guard and the request lifecycle
  (QUEUED→PREFILL→DECODE→DONE/CANCELLED).
* :mod:`cache_manager` — slot allocation/roll-in/reset on top of the
  ``modules/attention.KVCache`` collection layout (no reallocation between
  requests), plus :class:`PrefixCache` — the host-managed, ref-counted,
  LRU-evicted store of prompt-prefix KV blocks behind the engine's
  shared-prompt admission path (longest-match lookup → suffix prefill of
  the uncached tail → insert-on-miss; streams bit-identical to cache-off,
  disable with ``ServingEngine(prefix_cache=None)``).
* :mod:`paging` — the PAGED KV layout (``ServingEngine(kv_page_size=)``,
  ISSUE 10): :class:`PageAllocator` (ref-counted, free-listed page pool) +
  :class:`PagedCacheManager` (per-slot device-resident block tables; the
  decode chunk gathers the logical view, runs the exact row math, and
  scatters back its write window). Buys free-page admission packing under
  mixed-length traffic, ZERO-COPY copy-on-write prefix sharing (insert
  pins pages, hits map them — ``copy_bytes`` stays 0), and page-granular
  poison quarantine; streams stay bit-identical to the row layout and
  ``decode_compilations`` stays 1.
* :mod:`tiering` — the host-RAM page tier (``ServingEngine(
  kv_host_pages=)``, ISSUE 19): :class:`HostPageStore` holds spilled pool
  pages as fingerprinted host numpy blocks; the reclaim valve spills cold
  prefix entries there instead of evicting, and admission prefetches
  matched pages back while the request queues — eviction cliff becomes a
  hit-rate slope, streams stay bit-identical, host-sync budgets unchanged.
* :mod:`metrics` — TTFT / decode throughput / queue wait / occupancy /
  preemption counters plus the fault-tolerance counters (sheds, rejects,
  quarantines, dispatch retries, health), exported as a plain dict snapshot
  and (optionally) onto a ``utils.timeline.Timeline``.
* :mod:`faults` — deterministic, schedule-driven fault injection
  (``FaultInjector``) for the engine's chaos hooks: dispatch failures,
  poisoned readbacks, prefill faults, clock skew.
* :mod:`router` — :class:`ReplicaRouter` (ISSUE 14): N engine replicas
  behind one ``submit()`` — queue-depth + projected-page-pressure
  balancing, shared-prefix affinity with an overcommit guard,
  drain-around DEGRADED/HALTED, and bit-identical re-homing of a halted
  replica's requeued work to survivors (``tokens_lost == 0``).
* :mod:`disagg` — :class:`DisaggregatedServer`/:class:`PrefillWorker`
  (ISSUE 14): dedicated prefill workers hand finished contexts to the
  paged decode engine as zero-copy PAGE-TABLE handoffs
  (``PageAllocator.copy_bytes`` stays 0 on the shared-pool path; an
  explicit export/import device transfer covers distinct pools), so
  bursty prefill load cannot inflate steady-state decode TPOT.
* :mod:`traffic` — the deterministic open-loop load harness (ISSUE 11):
  seeded multi-tenant workload generation (Poisson + bursty/diurnal
  arrivals, chat vs long-doc length mixes) materialized as a
  byte-identical arrival tape, replayed through the engine on a
  :class:`VirtualClock` so the per-tenant TTFT/TPOT/goodput/attainment
  report is reproducible to the byte (``bench.py --child-traffic``).

Observability (ISSUE 8, ``neuronx_distributed_tpu/observability``): the
metrics above live in a shared ``MetricsRegistry`` (Prometheus/JSON
export, log-bucketed latency histograms incl. TTFT/TPOT percentiles);
with a ``Timeline`` attached every request renders as one connected
Perfetto flow (submit → admission → prefill → decode chunks → retire);
a ``FlightRecorder`` auto-dumps a redacted post-mortem on ``HALTED``
(``flight_dir=``); ``profile_dir=`` captures a ``jax.profiler`` trace of
decode chunks [2, 5). All of it adds ZERO device→host syncs on the hot
path (tests/serving/test_host_sync.py pins the budgets).

Robustness contract (chaos-tested in ``tests/serving/test_faults.py``):
deadlines and queue timeouts shed to ``TIMED_OUT``; a failed donated decode
dispatch recovers through the preemption machinery (streams bit-identical)
with bounded consecutive retries before ``HALTED``; poisoned slots are
quarantined out of the rotation without corrupting neighbors; a bounded
queue rejects with :class:`~neuronx_distributed_tpu.serving.engine.
RejectedError`; ``drain()`` finishes in-flight work while admitting nothing
new; ``health()`` reports ``OK/DEGRADED/DRAINING/HALTED``.
"""

from neuronx_distributed_tpu.quantization.config import QuantConfig
from neuronx_distributed_tpu.serving.cache_manager import (
    PrefixCache,
    PrefixEntry,
    SlotCacheManager,
)
from neuronx_distributed_tpu.serving.engine import (
    EngineHealth,
    RejectedError,
    ServingEngine,
)
from neuronx_distributed_tpu.serving.disagg import (
    DisaggregatedServer,
    PrefillWorker,
)
from neuronx_distributed_tpu.serving.faults import (
    FaultInjector,
    InjectedDispatchError,
    InjectedDraftError,
    InjectedFault,
    InjectedHandoffError,
    InjectedPrefetchError,
    InjectedPrefillError,
    InjectedSpillError,
)
from neuronx_distributed_tpu.serving.metrics import ServingMetrics
from neuronx_distributed_tpu.serving.paging import (
    ExportedContext,
    PageAllocator,
    PagedCacheManager,
    PageExhausted,
    StagedContext,
)
from neuronx_distributed_tpu.serving.router import (
    RID_STRIDE,
    ReplicaRouter,
    WatchdogConfig,
)
from neuronx_distributed_tpu.serving.sched import (
    FairnessConfig,
    FeedbackConfig,
    FifoPolicy,
    PriorityConfig,
    SchedulingPolicy,
    SloPolicy,
    make_policy,
)
from neuronx_distributed_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
)
from neuronx_distributed_tpu.serving.tiering import HostPageStore
from neuronx_distributed_tpu.serving.transport import (
    ChaosTransport,
    Envelope,
    InProcessTransport,
    PartitionedError,
    TransportError,
    TransportTimeout,
)
from neuronx_distributed_tpu.serving.traffic import (
    Arrival,
    TenantProfile,
    VirtualClock,
    build_report,
    generate_tape,
    replay,
    tape_bytes,
)

__all__ = [
    "Arrival",
    "ChaosTransport",
    "DisaggregatedServer",
    "EngineHealth",
    "Envelope",
    "ExportedContext",
    "FairnessConfig",
    "FaultInjector",
    "FeedbackConfig",
    "FifoPolicy",
    "HostPageStore",
    "InProcessTransport",
    "InjectedDispatchError",
    "InjectedDraftError",
    "InjectedFault",
    "InjectedHandoffError",
    "InjectedPrefetchError",
    "InjectedPrefillError",
    "InjectedSpillError",
    "PageAllocator",
    "PageExhausted",
    "PagedCacheManager",
    "PartitionedError",
    "PrefillWorker",
    "PrefixCache",
    "PrefixEntry",
    "PriorityConfig",
    "QuantConfig",
    "RID_STRIDE",
    "RejectedError",
    "ReplicaRouter",
    "Request",
    "RequestState",
    "Scheduler",
    "SchedulingPolicy",
    "ServingEngine",
    "ServingMetrics",
    "SloPolicy",
    "SlotCacheManager",
    "StagedContext",
    "TenantProfile",
    "TransportError",
    "TransportTimeout",
    "VirtualClock",
    "WatchdogConfig",
    "build_report",
    "generate_tape",
    "make_policy",
    "replay",
    "tape_bytes",
]

"""Slot-level KV-cache management for the serving engine.

The engine owns ONE cache collection shaped ``(num_slots, max_seq_len)``
(per layer), allocated once and never reallocated between requests. Batch
rows are request SLOTS; the column layout is the shared-cursor scheme the
repo's KV cache already speaks for left-padded batches:

* ``index`` is a SINGLE write cursor shared by every slot (the KVCache
  contract). All active slots write their decode K/V at the same column.
* A newly admitted request's prompt is placed so its LAST token sits at
  column ``cursor - 1`` — exactly the left-padded layout, produced by
  rolling the batch-1 prefill cache row right by ``cursor - P`` (P = the
  padded prefill length whose index the row carries).
* Columns a slot does not cover are ``kv_valid=False``; per-row attention
  masking and RoPE positions already run off validity counts
  (``valid_count_below``), so gap columns and cursor jumps are invisible to
  the math. Raising the cursor past a slot's last write merely leaves
  invalid gap columns behind — which is how a LONG prompt can be admitted
  next to slots that joined earlier.
* Freeing a slot clears its ``kv_valid`` row (``reset_cache_slot``);
  draining the engine rewinds the cursor (``reset_cache``). Storage is
  reused in place.

The cursor therefore advances monotonically while any slot is active; the
engine preempts-and-rewinds when it would run off ``max_seq_len`` (see
``ServingEngine``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from neuronx_distributed_tpu.modules.attention import (
    cache_batch_axis,
    cache_leaf_name,
    reset_cache,
    reset_cache_slot,
)


def _admit_row(big, row, slot, padded_len, cursor):
    """Merge a batch-1 prefill cache ``row`` into ``big`` at batch index
    ``slot``, rolled so the prompt's last token lands at column
    ``cursor - 1``. ``index`` leaves are set to ``cursor`` (the shared
    cursor may jump forward to fit a long prompt; gap columns stay
    invalid for every row)."""
    shift = cursor - padded_len

    def fn(path, b_leaf, r_leaf):
        name = cache_leaf_name(path)
        ax = cache_batch_axis(name, b_leaf.ndim)
        if ax is None:  # shared write cursor
            return jnp.full_like(b_leaf, cursor)
        # k/v (..., B, L, Hkv, D) and kv_valid (..., B, L): the cache-length
        # axis sits right after the batch axis in both layouts
        r = jnp.roll(r_leaf, shift, axis=ax + 1)
        return jax.lax.dynamic_update_slice_in_dim(b_leaf, r, slot, axis=ax)

    return jax.tree_util.tree_map_with_path(fn, big, row)


class SlotCacheManager:
    """Host-side owner of the engine's cache collection + slot free list.

    All device work is three jitted programs compiled once each:
    admission roll-in, per-slot free, and full reset. Each DONATES the big
    cache pytree — a slot event updates the ``(num_slots, max_seq_len)``
    storage in place instead of materializing a copy, matching the decode
    step's donation regime (the manager's reference is replaced by the
    result, so the consumed buffer is never touched again)."""

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self.cache = None  # allocated lazily from the first prefill row
        self.cursor = 0  # host mirror of the shared `index` cursor
        self._free = list(range(num_slots))
        self._quarantined: set = set()  # slots pulled from rotation for good
        self._admit_fn = jax.jit(_admit_row, donate_argnums=(0,))
        self._free_fn = jax.jit(reset_cache_slot, donate_argnums=(0,))
        self._reset_fn = jax.jit(reset_cache, donate_argnums=(0,))

    # --- slot accounting ---------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free) - len(self._quarantined)

    @property
    def usable_slots(self) -> int:
        """Slots still in the rotation (not quarantined)."""
        return self.num_slots - len(self._quarantined)

    @property
    def quarantined_slots(self) -> list:
        return sorted(self._quarantined)

    def acquire(self) -> int:
        return self._free.pop(0)

    def quarantine(self, slot: int) -> None:
        """Pull ``slot`` out of the rotation permanently (poisoned readback
        — its cache row is suspect and must never host another request).
        The caller owns clearing the engine-side slot bookkeeping."""
        self._quarantined.add(slot)
        if slot in self._free:
            self._free.remove(slot)

    # --- device-state transitions ------------------------------------------

    def allocate_from(self, row_cache) -> None:
        """Build the (num_slots, …) cache collection from a batch-1 prefill
        row's structure — zeros everywhere; happens exactly once."""

        def fn(path, r_leaf):
            name = cache_leaf_name(path)
            ax = cache_batch_axis(name, r_leaf.ndim)
            if ax is None:
                return jnp.zeros_like(r_leaf)
            shape = list(r_leaf.shape)
            shape[ax] = self.num_slots
            return jnp.zeros(tuple(shape), r_leaf.dtype)

        self.cache = jax.tree_util.tree_map_with_path(fn, row_cache)

    def admit(self, row_cache, slot: int, padded_len: int,
              cursor: Optional[int] = None) -> None:
        """Roll a prefill row into ``slot``. ``cursor`` (default: keep, but
        never below ``padded_len``) becomes the new shared write cursor."""
        if self.cache is None:
            if self.cursor > 0:
                # a first-ever allocation always starts at cursor 0; a
                # missing cache with an advanced cursor means a donating
                # consumer lost it mid-flight (take() never paired with
                # update_after_decode/restore) — reallocating zeros would
                # silently corrupt every running slot's context
                raise RuntimeError(
                    "cache collection missing mid-flight (cursor "
                    f"{self.cursor}): a take() was never paired with "
                    "update_after_decode/restore"
                )
            self.allocate_from(row_cache)
        target = max(self.cursor, padded_len) if cursor is None else cursor
        if target < padded_len:
            raise ValueError(
                f"cursor {target} < padded prefill length {padded_len}: the "
                "prompt's last token cannot land left of its own start"
            )
        self.cache = self._admit_fn(
            self.cache, row_cache,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(padded_len, jnp.int32),
            jnp.asarray(target, jnp.int32),
        )
        self.cursor = target

    def free(self, slot: int) -> None:
        """Clear the slot's ``kv_valid`` row and return it to the free list
        — immediately re-admittable, no reallocation. A quarantined slot is
        cleared but never rejoins the rotation."""
        if self.cache is not None:
            self.cache = self._free_fn(self.cache, jnp.asarray(slot, jnp.int32))
        if slot not in self._quarantined:
            self._free.append(slot)
            self._free.sort()

    def take(self):
        """Hand the cache to a donating consumer (the engine's decode
        chunk). The manager's reference is dropped so nothing can touch the
        donated buffers; the caller MUST give the successor back via
        :meth:`update_after_decode`, or :meth:`restore` the original if the
        dispatch raised."""
        cache, self.cache = self.cache, None
        return cache

    def restore(self, cache) -> None:
        """Re-adopt a cache whose donating dispatch FAILED (cursor
        untouched). If the failure happened after XLA consumed the buffers,
        the next device use raises jax's deleted-buffer error — loud, which
        is the point: without the restore, admission would silently
        reallocate a zeroed cache under still-active slots."""
        self.cache = cache

    def recover(self, cache) -> bool:
        """Re-adopt storage after a FAILED donating dispatch whose requests
        are being requeued (cursor rewinds to 0 either way). When the
        failure left the buffers unconsumed, keep the allocation and
        invalidate it in place (one device program); when XLA already
        consumed them, drop to lazy reallocation — the next admission
        rebuilds zeros, which is safe precisely because every slot has been
        vacated. Returns whether the storage survived."""
        consumed = any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree_util.tree_leaves(cache)
        )
        self.cursor = 0
        if consumed:
            self.cache = None
            return False
        self.cache = cache
        if self.cache is not None:
            self.cache = self._reset_fn(self.cache)
        return True

    def release_all_slots(self) -> None:
        """Return every non-quarantined slot to the free list — HOST
        bookkeeping only, for callers about to :meth:`reset` (which
        invalidates all rows in one device program; per-slot :meth:`free`
        dispatches would be redundant)."""
        self._free = [
            s for s in range(self.num_slots) if s not in self._quarantined
        ]

    def update_after_decode(self, new_cache, steps: int = 1) -> None:
        """Adopt the cache returned by a decode dispatch; ``steps`` is how
        many write columns the fused chunk actually consumed (its on-device
        clamp stops the cursor early when every slot froze)."""
        self.cache = new_cache
        self.cursor += steps

    def reset(self) -> None:
        """Rewind the cursor and invalidate every slot's context (engine
        drain / preemption). Slot ownership is the engine's to clear."""
        self.cursor = 0
        if self.cache is not None:
            self.cache = self._reset_fn(self.cache)

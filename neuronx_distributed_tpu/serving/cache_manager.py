"""Slot-level KV-cache management for the serving engine.

The engine owns ONE cache collection shaped ``(num_slots, max_seq_len)``
(per layer), allocated once and never reallocated between requests. Batch
rows are request SLOTS; the column layout is the shared-cursor scheme the
repo's KV cache already speaks for left-padded batches:

* ``index`` is a SINGLE write cursor shared by every slot (the KVCache
  contract). All active slots write their decode K/V at the same column.
* A newly admitted request's prompt is placed so its LAST token sits at
  column ``cursor - 1`` — exactly the left-padded layout, produced by
  rolling the batch-1 prefill cache row right by ``cursor - P`` (P = the
  padded prefill length whose index the row carries).
* Columns a slot does not cover are ``kv_valid=False``; per-row attention
  masking and RoPE positions already run off validity counts
  (``valid_count_below``), so gap columns and cursor jumps are invisible to
  the math. Raising the cursor past a slot's last write merely leaves
  invalid gap columns behind — which is how a LONG prompt can be admitted
  next to slots that joined earlier.
* Freeing a slot clears its ``kv_valid`` row (``reset_cache_slot``);
  draining the engine rewinds the cursor (``reset_cache``). Storage is
  reused in place.

The cursor therefore advances monotonically while any slot is active; the
engine preempts-and-rewinds when it would run off ``max_seq_len`` (see
``ServingEngine``).

This module also owns :class:`PrefixCache` — the host-managed, ref-counted,
LRU-evicted store of prompt-prefix KV blocks behind the engine's
prefix-reuse admission path (lookup → longest-match reuse → suffix prefill
→ insert-on-miss). Entries are compact COPIES extracted from prefill rows
(``modules/attention.extract_cache_prefix``), so they are immune to the
donation regime above: a donated decode consuming the big cache, a
quarantined slot, or a dropped-for-reallocation recovery never touches a
stored prefix.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from neuronx_distributed_tpu.modules.attention import (
    cache_batch_axis,
    cache_leaf_name,
    reset_cache,
    reset_cache_slot,
)
from neuronx_distributed_tpu.observability.programs import per_instance


def _admit_row(big, row, slot, padded_len, cursor):
    """Merge a batch-1 prefill cache ``row`` into ``big`` at batch index
    ``slot``, rolled so the prompt's last token lands at column
    ``cursor - 1``. ``index`` leaves are set to ``cursor`` (the shared
    cursor may jump forward to fit a long prompt; gap columns stay
    invalid for every row)."""
    shift = cursor - padded_len

    def fn(path, b_leaf, r_leaf):
        name = cache_leaf_name(path)
        ax = cache_batch_axis(name, b_leaf.ndim)
        if ax is None:  # shared write cursor
            return jnp.full_like(b_leaf, cursor)
        # k/v (..., B, L, Hkv, D) and kv_valid (..., B, L): the cache-length
        # axis sits right after the batch axis in both layouts
        r = jnp.roll(r_leaf, shift, axis=ax + 1)
        return jax.lax.dynamic_update_slice_in_dim(b_leaf, r, slot, axis=ax)

    return jax.tree_util.tree_map_with_path(fn, big, row)


class SlotCacheManager:
    """Host-side owner of the engine's cache collection + slot free list.

    All device work is three jitted programs compiled once each:
    admission roll-in, per-slot free, and full reset. Each DONATES the big
    cache pytree — a slot event updates the ``(num_slots, max_seq_len)``
    storage in place instead of materializing a copy, matching the decode
    step's donation regime (the manager's reference is replaced by the
    result, so the consumed buffer is never touched again)."""

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self.cache = None  # allocated lazily from the first prefill row
        self.cursor = 0  # host mirror of the shared `index` cursor
        self._free = list(range(num_slots))
        self._quarantined: set = set()  # slots pulled from rotation for good
        # per_instance: a module-level helper jitted directly would share
        # its pjit cache (and _cache_size) across managers in this jax —
        # fresh function objects keep compile accounting per-manager
        self._admit_fn = jax.jit(per_instance(_admit_row), donate_argnums=(0,))
        self._free_fn = jax.jit(per_instance(reset_cache_slot), donate_argnums=(0,))
        self._reset_fn = jax.jit(per_instance(reset_cache), donate_argnums=(0,))
        # TP serving (ISSUE 14): optional placement hook applied once at
        # allocation — the engine installs the partitioner's kv-head-axis
        # placement so the donated programs inherit a committed layout
        self.placement = None

    def register_programs(self, programs, prefix: str = "") -> None:
        """Wrap the manager's jitted programs in a
        :class:`~neuronx_distributed_tpu.observability.programs.
        ProgramLedger` (ISSUE 12). Called by the engine after construction;
        the proxies forward ``_cache_size()`` so nothing else changes."""
        self._admit_fn = programs.wrap(f"{prefix}cache_admit", self._admit_fn)
        self._free_fn = programs.wrap(f"{prefix}cache_free", self._free_fn)
        self._reset_fn = programs.wrap(f"{prefix}cache_reset", self._reset_fn)

    # --- HBM accounting ----------------------------------------------------

    @property
    def nbytes(self) -> int:
        """Bytes of the live cache collection (leaf metadata — no sync;
        0 before first allocation or while a donating consumer holds it)."""
        from neuronx_distributed_tpu.observability.hbm import tree_nbytes

        return tree_nbytes(self.cache) if self.cache is not None else 0

    @property
    def slot_nbytes(self) -> int:
        """Approximate bytes one slot row occupies (the HBM ledger's
        ``plan()`` unit for row-mode capacity questions)."""
        return self.nbytes // self.num_slots if self.num_slots else 0

    # --- slot accounting ---------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free) - len(self._quarantined)

    @property
    def usable_slots(self) -> int:
        """Slots still in the rotation (not quarantined)."""
        return self.num_slots - len(self._quarantined)

    @property
    def quarantined_slots(self) -> list:
        return sorted(self._quarantined)

    def acquire(self) -> int:
        return self._free.pop(0)

    def quarantine(self, slot: int) -> None:
        """Pull ``slot`` out of the rotation permanently (poisoned readback
        — its cache row is suspect and must never host another request).
        The caller owns clearing the engine-side slot bookkeeping."""
        self._quarantined.add(slot)
        if slot in self._free:
            self._free.remove(slot)

    # --- device-state transitions ------------------------------------------

    def allocate_from(self, row_cache) -> None:
        """Build the (num_slots, …) cache collection from a batch-1 prefill
        row's structure — zeros everywhere; happens exactly once."""

        def fn(path, r_leaf):
            name = cache_leaf_name(path)
            ax = cache_batch_axis(name, r_leaf.ndim)
            if ax is None:
                return jnp.zeros_like(r_leaf)
            shape = list(r_leaf.shape)
            shape[ax] = self.num_slots
            return jnp.zeros(tuple(shape), r_leaf.dtype)

        self.cache = jax.tree_util.tree_map_with_path(fn, row_cache)
        if self.placement is not None:
            self.cache = self.placement(self.cache)

    def admit(self, row_cache, slot: int, padded_len: int,
              cursor: Optional[int] = None) -> None:
        """Roll a prefill row into ``slot``. ``cursor`` (default: keep, but
        never below ``padded_len``) becomes the new shared write cursor."""
        if self.cache is None:
            if self.cursor > 0:
                # a first-ever allocation always starts at cursor 0; a
                # missing cache with an advanced cursor means a donating
                # consumer lost it mid-flight (take() never paired with
                # update_after_decode/restore) — reallocating zeros would
                # silently corrupt every running slot's context
                raise RuntimeError(
                    "cache collection missing mid-flight (cursor "
                    f"{self.cursor}): a take() was never paired with "
                    "update_after_decode/restore"
                )
            self.allocate_from(row_cache)
        target = max(self.cursor, padded_len) if cursor is None else cursor
        if target < padded_len:
            raise ValueError(
                f"cursor {target} < padded prefill length {padded_len}: the "
                "prompt's last token cannot land left of its own start"
            )
        self.cache = self._admit_fn(
            self.cache, row_cache,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(padded_len, jnp.int32),
            jnp.asarray(target, jnp.int32),
        )
        self.cursor = target

    def free(self, slot: int) -> None:
        """Clear the slot's ``kv_valid`` row and return it to the free list
        — immediately re-admittable, no reallocation. A quarantined slot is
        cleared but never rejoins the rotation."""
        if self.cache is not None:
            self.cache = self._free_fn(self.cache, jnp.asarray(slot, jnp.int32))
        if slot not in self._quarantined:
            self._free.append(slot)
            self._free.sort()

    def take(self):
        """Hand the cache to a donating consumer (the engine's decode
        chunk). The manager's reference is dropped so nothing can touch the
        donated buffers; the caller MUST give the successor back via
        :meth:`update_after_decode`, or :meth:`restore` the original if the
        dispatch raised."""
        cache, self.cache = self.cache, None
        return cache

    def restore(self, cache) -> None:
        """Re-adopt a cache whose donating dispatch FAILED (cursor
        untouched). If the failure happened after XLA consumed the buffers,
        the next device use raises jax's deleted-buffer error — loud, which
        is the point: without the restore, admission would silently
        reallocate a zeroed cache under still-active slots."""
        self.cache = cache

    def recover(self, cache) -> bool:
        """Re-adopt storage after a FAILED donating dispatch whose requests
        are being requeued (cursor rewinds to 0 either way). When the
        failure left the buffers unconsumed, keep the allocation and
        invalidate it in place (one device program); when XLA already
        consumed them, drop to lazy reallocation — the next admission
        rebuilds zeros, which is safe precisely because every slot has been
        vacated. Returns whether the storage survived."""
        consumed = any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree_util.tree_leaves(cache)
        )
        self.cursor = 0
        if consumed:
            self.cache = None
            return False
        self.cache = cache
        if self.cache is not None:
            self.cache = self._reset_fn(self.cache)
        return True

    def release_all_slots(self) -> None:
        """Return every non-quarantined slot to the free list — HOST
        bookkeeping only, for callers about to :meth:`reset` (which
        invalidates all rows in one device program; per-slot :meth:`free`
        dispatches would be redundant)."""
        self._free = [
            s for s in range(self.num_slots) if s not in self._quarantined
        ]

    def update_after_decode(self, new_cache, steps: int = 1) -> None:
        """Adopt the cache returned by a decode dispatch; ``steps`` is how
        many write columns the fused chunk actually consumed (its on-device
        clamp stops the cursor early when every slot froze)."""
        self.cache = new_cache
        self.cursor += steps

    def reset(self) -> None:
        """Rewind the cursor and invalidate every slot's context (engine
        drain / preemption). Slot ownership is the engine's to clear."""
        self.cursor = 0
        if self.cache is not None:
            self.cache = self._reset_fn(self.cache)


# --- host-managed prefix KV cache ---------------------------------------------


@dataclasses.dataclass
class PrefixEntry:
    """One stored prompt prefix: ``tokens`` (the full token path, length
    ``m``), its compact device KV block ``tree`` (``bucket`` columns, token
    0 at column 0 — a COPY made at insert time, never a view of any
    engine-owned or donated buffer), the integrity ``fingerprint`` +
    ``shapes`` recorded at insert (validated on every reuse), and ``refs``
    — the pin count that protects an entry backing an in-flight suffix
    prefill from eviction."""

    tokens: Tuple[int, ...]
    tree: Any
    bucket: int
    # device scalar (or host float in tests): kept un-synced at insert so
    # a miss admission never blocks on it; reuse-time validation floats it
    fingerprint: Any
    shapes: Tuple[Tuple[int, ...], ...]
    refs: int = 0
    # PAGED engines (serving/paging.py): the entry holds no KV copy at all
    # (``tree`` is None) — just the ref-counted pool page ids its tokens
    # live in, mapped copy-on-write into a hitting slot's block table
    page_ids: Optional[Tuple[int, ...]] = None
    # integrity sentinel (ISSUE 20): per-page content fingerprints of
    # ``page_ids`` recorded at insert (device uint32 vector, bucketed —
    # positions past ``len(page_ids)`` are padding). Reuse recomputes the
    # used prefix and compares bit-exactly, closing the content gap that
    # host-side ``pages_live`` accounting cannot see (an HBM bit flip
    # leaves allocation/pins/quarantine perfectly healthy)
    page_fp: Any = None
    # tiered KV (ISSUE 19): a SPILLED entry's pages live in the engine's
    # HostPageStore under these ids instead (``page_ids`` is None while
    # host-resident). The entry STAYS in the trie so lookups keep matching
    # it; a prefetch re-homes it device-side (host_ids -> page_ids) before
    # any slot maps it. Exactly one of page_ids/host_ids is set for a
    # paged entry that still holds content
    host_ids: Optional[Tuple[int, ...]] = None
    # which tier the entry's NEXT hit is attributed to: "host" right after
    # a prefetch (the hit only exists because the host tier kept the
    # pages), reset to "device" once that hit is recorded
    hit_tier: str = "device"

    @property
    def m(self) -> int:
        return len(self.tokens)


class _TrieNode:
    __slots__ = ("children", "entry")

    def __init__(self):
        self.children: Dict[int, "_TrieNode"] = {}
        self.entry: Optional[PrefixEntry] = None


class PrefixCache:
    """Host-managed, ref-counted, LRU-evicted store of prompt-prefix KV
    blocks, keyed by a token trie.

    The serving engine consults it at admission: the longest stored prefix
    of the incoming context (capped at ``context - 1`` — at least one token
    must run so the admission has next-token logits to sample from) is
    copied into the slot's row and only the uncached tail is prefilled.
    A stored entry's first ``d`` columns serve ANY context sharing its
    first ``d`` tokens, so a single long entry covers every shorter shared
    prefix — lookup walks the trie to the deepest reachable node and uses
    any entry beneath it.

    Matches shorter than ``min_match`` tokens are misses (a tiny reuse
    does not pay for the extra programs), and contexts shorter than
    ``min_match`` are never stored. ``max_entries=0`` disables the cache
    entirely — the engine then runs today's exact full-prefill path.

    Eviction is LRU over entries (hits, covers and inserts refresh
    recency) and NEVER frees a pinned entry (``refs > 0`` — an in-flight
    suffix prefill holds one); if every entry is pinned the store
    temporarily overflows rather than corrupt an in-flight admission.
    The engine owns the counters (metrics) — this class just reports what
    each call did."""

    def __init__(self, max_entries: int = 32, min_match: int = 8):
        if min_match < 1:
            raise ValueError(f"min_match must be >= 1, got {min_match}")
        self.max_entries = max_entries
        self.min_match = min_match
        self._root = _TrieNode()
        self._lru: "OrderedDict[Tuple[int, ...], PrefixEntry]" = OrderedDict()
        # called with each entry as it leaves the store (LRU eviction,
        # forced eviction, clear) — the PAGED engine releases the entry's
        # pool page refs here so a dropped entry can never leak pages
        self.on_evict: Optional[Any] = None

    # --- introspection ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0

    def __len__(self) -> int:
        return len(self._lru)

    @property
    def entries(self):
        """Stored entries, least-recently-used first."""
        return list(self._lru.values())

    @property
    def tokens_stored(self) -> int:
        return sum(e.m for e in self._lru.values())

    @property
    def nbytes(self) -> int:
        """Bytes of stored entry KV copies (leaf metadata — no sync).
        Paged entries hold page ids, not copies, so they report 0 here;
        their bytes live in the page pool's accounting."""
        from neuronx_distributed_tpu.observability.hbm import tree_nbytes

        return sum(
            tree_nbytes(e.tree) for e in self._lru.values()
            if e.tree is not None
        )

    def _walk(self, tokens) -> Tuple[_TrieNode, int]:
        """Deepest trie node reachable along ``tokens`` and its depth.
        Every live node has at least one entry in its subtree (eviction
        prunes entry-less childless chains), so reaching depth ``d`` means
        some stored entry shares the first ``d`` tokens."""
        node, depth = self._root, 0
        for t in tokens:
            nxt = node.children.get(int(t))
            if nxt is None:
                break
            node, depth = nxt, depth + 1
        return node, depth

    @staticmethod
    def _subtree_entry(node: _TrieNode) -> Optional[PrefixEntry]:
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    def match_len(self, tokens) -> int:
        """Read-only longest USABLE match length for ``tokens`` (0 when
        below ``min_match``) — the scheduler's effective-prefill-cost peek;
        no LRU state moves."""
        if not self.enabled:
            return 0
        _, depth = self._walk(tokens)
        use = min(depth, len(tokens) - 1)
        return use if use >= self.min_match else 0

    # --- lookup / insert ----------------------------------------------------

    def lookup(self, tokens) -> Optional[Tuple[PrefixEntry, int]]:
        """Longest-match lookup: ``(entry, m_use)`` where the entry's first
        ``m_use`` columns are the reusable prefix KV, or ``None`` on a miss
        (no match, or a match shorter than ``min_match``). Refreshes the
        matched entry's recency; the caller pins it (:meth:`pin`) for the
        duration of the suffix prefill."""
        if not self.enabled:
            return None
        node, depth = self._walk(tokens)
        m_use = min(depth, len(tokens) - 1)
        if m_use < self.min_match:
            return None
        entry = self._subtree_entry(node)
        if entry is None:  # unreachable for a live trie; be safe
            return None
        self._lru.move_to_end(entry.tokens)
        return entry, m_use

    def peek(self, tokens) -> Optional[Tuple[PrefixEntry, int]]:
        """:meth:`lookup` without the LRU refresh — the tiered-KV
        admission pre-pass (ISSUE 19) scans QUEUED requests for host-tier
        entries worth prefetching, and a scan must not reorder recency for
        requests that may never be admitted (the real lookup at admission
        time still refreshes)."""
        if not self.enabled:
            return None
        node, depth = self._walk(tokens)
        m_use = min(depth, len(tokens) - 1)
        if m_use < self.min_match:
            return None
        entry = self._subtree_entry(node)
        if entry is None:
            return None
        return entry, m_use

    def covers(self, tokens) -> bool:
        """Whether some stored entry already extends (or equals) ``tokens``
        — inserting them again would add nothing. Refreshes the covering
        entry so the hot prefix stays resident."""
        if not self.enabled:
            return False
        node, depth = self._walk(tokens)
        if depth < len(tokens):
            return False
        entry = self._subtree_entry(node)
        if entry is not None:
            self._lru.move_to_end(entry.tokens)
        return entry is not None

    def insert(self, tokens, tree, fingerprint,
               bucket: int) -> Tuple[Optional[PrefixEntry], int]:
        """Store a prefix block. Returns ``(entry, n_evicted)`` — entry is
        ``None`` when the insert was skipped (disabled, too short, or
        already covered). Evicts least-recently-used UNPINNED entries until
        the store fits ``max_entries``."""
        key = tuple(int(t) for t in tokens)
        if not self.enabled or len(key) < self.min_match:
            return None, 0
        if self.covers(key):
            return None, 0
        entry = PrefixEntry(
            tokens=key, tree=tree, bucket=bucket,
            fingerprint=fingerprint,
            shapes=tuple(
                tuple(leaf.shape) for leaf in jax.tree_util.tree_leaves(tree)
            ),
        )
        node = self._root
        for t in key:
            node = node.children.setdefault(t, _TrieNode())
        node.entry = entry
        self._lru[key] = entry
        evicted = 0
        while len(self._lru) > self.max_entries:
            victim = next(
                (e for e in self._lru.values() if e.refs == 0 and e is not entry),
                None,
            )
            if victim is None:  # everything pinned: overflow, never corrupt
                break
            self.evict_entry(victim)
            evicted += 1
        return entry, evicted

    # --- pins / eviction ----------------------------------------------------

    def pin(self, entry: PrefixEntry) -> None:
        entry.refs += 1

    def release(self, entry: PrefixEntry) -> None:
        entry.refs = max(0, entry.refs - 1)

    def release_all(self) -> None:
        """Drop every pin — the engine's recovery/halt paths call this so a
        failed in-flight suffix prefill can never leave a stale ref that
        blocks eviction forever (PR 3's recovery contract)."""
        for e in self._lru.values():
            e.refs = 0

    def evict_entry(self, entry: PrefixEntry) -> bool:
        """Remove one entry (LRU eviction, or forced — validation failure /
        poison), pruning the trie chain it leaves behind."""
        if self._lru.pop(entry.tokens, None) is None:
            return False
        if self.on_evict is not None:
            self.on_evict(entry)
        path = [self._root]
        for t in entry.tokens:
            nxt = path[-1].children.get(t)
            if nxt is None:
                return True  # trie already pruned past here
            path.append(nxt)
        path[-1].entry = None
        for depth in range(len(path) - 1, 0, -1):
            node = path[depth]
            if node.entry is None and not node.children:
                del path[depth - 1].children[entry.tokens[depth - 1]]
            else:
                break
        return True

    def clear(self) -> int:
        """Drop everything (the engine calls this on a weight swap — prefix
        KV computed under old params must never serve new-params traffic).
        Returns how many entries were dropped."""
        n = len(self._lru)
        if self.on_evict is not None:
            for e in self._lru.values():
                self.on_evict(e)
        self._root = _TrieNode()
        self._lru = OrderedDict()
        return n

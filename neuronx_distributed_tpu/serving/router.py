"""Replica router: N data-parallel serving engines behind one ``submit()``.

One TP-sharded engine is a single failure/capacity domain; production
traffic wants N of them (ISSUE 14, ROADMAP "Multi-chip serving"). The
router is deliberately a HOST-side policy layer — it owns no device state
and never touches a hot path:

* **Balancing** — each submission routes to the healthiest, least-loaded
  replica. The load signal is ``ServingEngine.load_score()``: active slots
  + queue depth + the replica's PROJECTED PAGE FOOTPRINT scaled to slot
  units. The page term is the overcommit fix (satellite of ISSUE 14):
  queue depth alone cannot see that a backlog of long shared-prefix
  contexts will claim a paged replica's whole pool, so affinity steering
  could pile work onto a replica that then lives at the page-pressure
  preemption wall. ``page_pressure() >= overcommit`` disqualifies a
  replica from affinity steering (and deprioritizes it for balancing)
  before that happens.
* **Prefix affinity** — a session whose prompt prefix is already resident
  in some replica's ``PrefixCache`` (CoW pool pages under paging) steers
  to that replica: the hit turns a full prefill into a suffix prefill and
  shares pages instead of duplicating them. Affinity never overrides
  health or the overcommit guard.
* **Drain-around** — DEGRADED replicas stop receiving new work while any
  OK replica exists; DRAINING/HALTED replicas receive nothing. A replica
  that HALTS mid-decode has, by the engine's halt contract, requeued every
  in-flight request with host-current tokens and keys — the router
  RE-HOMES that work to survivors (``ServingEngine.adopt``): each request
  keeps its rid (replicas mint from disjoint ``rid_base`` ranges), its
  streamed tokens, and its key, so the survivor's continuation is
  bit-identical and ``tokens_lost == 0`` (chaos-pinned in
  tests/serving/test_router.py).
* **One scrape** — replicas built by :meth:`ReplicaRouter.build` share one
  ``MetricsRegistry`` as engine-labeled metric families (the ISSUE 11
  machinery), so tenant/SLO attribution and the program/HBM ledgers of all
  replicas aggregate into a single Prometheus exposition with zero
  merging.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from neuronx_distributed_tpu.inference.generate import GenerationConfig
from neuronx_distributed_tpu.serving.engine import (
    EngineHealth,
    RejectedError,
    ServingEngine,
)
from neuronx_distributed_tpu.serving.scheduler import Request

# replicas mint rids from disjoint ranges so re-homed Request objects can
# never collide on a survivor (requests keep their rid across re-homing)
RID_STRIDE = 1_000_000_000


class ReplicaRouter:
    """Host-side request router over N ``ServingEngine`` replicas."""

    def __init__(self, replicas: List[ServingEngine],
                 affinity: bool = True,
                 affinity_overcommit: float = 0.85):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        bases = [e._next_rid for e in replicas]
        if len(set(bases)) != len(bases):
            raise ValueError(
                "replicas must mint request ids from disjoint rid_base "
                "ranges (ServingEngine(rid_base=i * router.RID_STRIDE)) — "
                "re-homed requests keep their rid on the survivor"
            )
        self.replicas = list(replicas)
        self.affinity = affinity
        self.affinity_overcommit = float(affinity_overcommit)
        self._dead: set = set()  # replica indices already drained/re-homed
        self.stats: Dict[str, int] = {
            "routed": 0,
            "affinity_hits": 0,
            "rehomed_requests": 0,
            "replicas_drained": 0,
            "spillovers": 0,
        }
        self.routed_by_replica = [0] * len(replicas)

    # --- construction -------------------------------------------------------

    @classmethod
    def build(cls, model, params, n_replicas: int, registry=None,
              engine_label: str = "replica", **engine_kwargs
              ) -> "ReplicaRouter":
        """Build N identically-configured replicas sharing ``params`` (one
        host copy — placement may still differ per mesh) and, when a
        ``registry`` is given, one labeled metrics registry
        (``{engine_label}{i}`` children) so all replicas scrape as one
        endpoint."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        replicas = []
        for i in range(n_replicas):
            kwargs = dict(engine_kwargs)
            if registry is not None:
                kwargs.setdefault("registry", registry)
                kwargs.setdefault("engine_label", f"{engine_label}{i}")
            replicas.append(
                ServingEngine(
                    model, params, rid_base=i * RID_STRIDE, **kwargs
                )
            )
        return cls(replicas)

    # --- routing ------------------------------------------------------------

    def _live(self) -> List[int]:
        return [i for i in range(len(self.replicas)) if i not in self._dead]

    def _accepting(self) -> List[int]:
        """Replica indices that may receive NEW work: OK first; DEGRADED
        only when no OK replica exists (drain-around); DRAINING/HALTED
        never."""
        ok, degraded = [], []
        for i in self._live():
            h = self.replicas[i].health()
            if h is EngineHealth.OK:
                ok.append(i)
            elif h is EngineHealth.DEGRADED:
                degraded.append(i)
        return ok if ok else degraded

    def _pick(self, prompt: np.ndarray,
              tenant: Optional[str] = None) -> List[int]:
        """Replica indices to try, best first. With a ``tenant`` the load
        score grows that tenant's attainment bias (ISSUE 16): under an
        SLO-scheduling replica set, a replica where this tenant's SLO is
        under water sorts later, so the tenant's next request lands where
        its SLO is healthiest. FIFO replicas bias 0.0 — order unchanged."""
        candidates = self._accepting()
        if not candidates:
            raise RejectedError(
                "no replica is accepting work (all draining/halted)",
                queue_depth=sum(
                    self.replicas[i].scheduler.queued for i in self._live()
                ),
            )
        order = sorted(
            candidates,
            key=lambda i: self.replicas[i].load_score(tenant=tenant),
        )
        if self.affinity:
            best_i, best_m = None, 0
            for i in candidates:
                e = self.replicas[i]
                if e.prefix is None:
                    continue
                if e.page_pressure() >= self.affinity_overcommit:
                    # the overcommit guard: a page-saturated replica's
                    # resident prefix is not worth living at its
                    # preemption wall — balance instead
                    continue
                m = e.prefix.match_len(prompt)
                if m > best_m:
                    best_i, best_m = i, m
            if best_i is not None:
                self.stats["affinity_hits"] += 1
                order = [best_i] + [i for i in order if i != best_i]
        return order

    def submit(
        self,
        prompt_ids,
        config: GenerationConfig = GenerationConfig(),
        key=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        deadline_s: Optional[float] = None,
        queue_timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> Request:
        """Route one request to the best replica (affinity → health →
        load), spilling to the next-best on a bounded-queue rejection;
        raises :class:`RejectedError` only when EVERY accepting replica
        refused."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        last_reject: Optional[RejectedError] = None
        for rank, i in enumerate(self._pick(prompt, tenant=tenant)):
            try:
                req = self.replicas[i].submit(
                    prompt, config, key=key, on_token=on_token,
                    deadline_s=deadline_s, queue_timeout_s=queue_timeout_s,
                    tenant=tenant, priority=priority,
                )
            except RejectedError as e:
                last_reject = e
                if rank == 0:
                    self.stats["spillovers"] += 1
                continue
            self.stats["routed"] += 1
            self.routed_by_replica[i] += 1
            return req
        assert last_reject is not None
        raise last_reject

    # --- stepping / fault handling ------------------------------------------

    def _rehome(self, dead_idx: int) -> int:
        """Move a HALTED replica's queued work (requeued in-flight victims
        included — the engine's halt contract put them back with
        host-current tokens/keys) to survivors. Returns how many requests
        moved; unfinished work with no accepting survivor stays queued on
        the dead replica for operator handoff."""
        dead = self.replicas[dead_idx]
        moved = 0
        for req in list(dead.scheduler.queued_requests):
            targets = self._accepting()
            targets = [t for t in targets if t != dead_idx]
            if not targets:
                break
            target = min(
                targets,
                key=lambda i: self.replicas[i].load_score(
                    tenant=req.tenant
                ),
            )
            cb = dead._on_token.pop(req.rid, None)
            self.replicas[target].adopt(req, on_token=cb)
            moved += 1
        self._dead.add(dead_idx)
        self.stats["replicas_drained"] += 1
        self.stats["rehomed_requests"] += moved
        return moved

    def step(self) -> bool:
        """One router iteration: re-home any newly-halted replica's work,
        then step every live replica that has work. Returns whether work
        remains anywhere."""
        for i in self._live():
            if self.replicas[i].health() is EngineHealth.HALTED:
                self._rehome(i)
        for i in self._live():
            e = self.replicas[i]
            if e.has_work:
                e.step()
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        """Step until no replica has work (or ``max_steps``); returns every
        request any replica has seen, by rid."""
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return self.requests

    # --- introspection ------------------------------------------------------

    @property
    def has_work(self) -> bool:
        for i in self._live():
            e = self.replicas[i]
            if e.has_work:
                return True
            # a halted replica makes no progress itself, but its requeued
            # work is the ROUTER's to move — run() must keep stepping
            # until the re-home happens
            if (
                e.health() is EngineHealth.HALTED
                and e.scheduler.queued > 0
            ):
                return True
        return False

    @property
    def requests(self) -> Dict[int, Request]:
        out: Dict[int, Request] = {}
        for e in self.replicas:
            out.update(e.scheduler.requests)
        return out

    def health(self) -> Dict[str, str]:
        """Per-replica health map plus the aggregate: ``ok`` while any
        replica accepts work, ``degraded`` when only DEGRADED replicas do,
        ``halted`` when none does."""
        per = {
            f"replica{i}": e.health().value
            for i, e in enumerate(self.replicas)
        }
        accepting = self._accepting() if self._live() else []
        if not accepting:
            agg = "halted"
        elif all(
            self.replicas[i].health() is EngineHealth.DEGRADED
            for i in accepting
        ):
            agg = "degraded"
        else:
            agg = "ok"
        return {"aggregate": agg, **per}

    def snapshot(self) -> dict:
        """Router bookkeeping + per-replica metrics snapshots (replicas
        built over one labeled registry ALSO aggregate into a single
        Prometheus scrape — this is the JSON view of the same data)."""
        return {
            "router": {
                **self.stats,
                "routed_by_replica": list(self.routed_by_replica),
                "health": self.health(),
            },
            "replicas": {
                f"replica{i}": e.metrics.snapshot()
                for i, e in enumerate(self.replicas)
            },
        }

"""Replica router: N data-parallel serving engines behind one ``submit()``.

One TP-sharded engine is a single failure/capacity domain; production
traffic wants N of them (ISSUE 14, ROADMAP "Multi-chip serving"). The
router is deliberately a HOST-side policy layer — it owns no device state
and never touches a hot path:

* **Balancing** — each submission routes to the healthiest, least-loaded
  replica. The load signal is ``ServingEngine.load_score()``: active slots
  + queue depth + the replica's PROJECTED PAGE FOOTPRINT scaled to slot
  units. The page term is the overcommit fix (satellite of ISSUE 14):
  queue depth alone cannot see that a backlog of long shared-prefix
  contexts will claim a paged replica's whole pool, so affinity steering
  could pile work onto a replica that then lives at the page-pressure
  preemption wall. ``page_pressure() >= overcommit`` disqualifies a
  replica from affinity steering (and deprioritizes it for balancing)
  before that happens.
* **Prefix affinity** — a session whose prompt prefix is already resident
  in some replica's ``PrefixCache`` (CoW pool pages under paging) steers
  to that replica: the hit turns a full prefill into a suffix prefill and
  shares pages instead of duplicating them. Affinity never overrides
  health or the overcommit guard.
* **Drain-around** — DEGRADED replicas stop receiving new work while any
  OK replica exists; DRAINING/HALTED replicas receive nothing. A replica
  that HALTS mid-decode has, by the engine's halt contract, requeued every
  in-flight request with host-current tokens and keys — the router
  RE-HOMES that work to survivors (``ServingEngine.adopt``): each request
  keeps its rid (replicas mint from disjoint ``rid_base`` ranges), its
  streamed tokens, and its key, so the survivor's continuation is
  bit-identical and ``tokens_lost == 0`` (chaos-pinned in
  tests/serving/test_router.py).

The elastic-fabric layer (ISSUE 18) adds the failure semantics a fixed
in-process replica set never needed:

* **Transport seam** — every router→replica interaction (submit, adopt,
  probe, restore) rides a
  :class:`~neuronx_distributed_tpu.serving.transport.InProcessTransport`
  (bit-identical to direct calls) or a fault-injecting
  :class:`~neuronx_distributed_tpu.serving.transport.ChaosTransport`.
  Messages carry ``(rid, seq)`` idempotency keys: a retried or duplicated
  adopt admits exactly once. An unreachable replica's submit spills to
  the next candidate instead of failing the caller.
* **Watchdog** — with a :class:`WatchdogConfig`, ``step()`` fires
  virtual-clock periodic health probes through the transport. Consecutive
  failures walk a replica OK→SUSPECT→DEGRADED→DEAD (thresholds per
  config); successes must accumulate (``recover_after`` in a row) to step
  back UP one level at a time, so a flapping replica is *held* at SUSPECT
  instead of oscillating between routable and dead. DEAD is terminal:
  the replica is fenced (``engine.fence`` — the in-process STONITH that
  guarantees it stops making progress) and its work re-homes through the
  existing halt/adopt contract with remaining deadline budgets and
  tenant/priority attribution intact.
* **Integrity vote** (ISSUE 20) — with
  ``WatchdogConfig(integrity_interval_s=...)``, the watchdog pass also
  fingerprints every routable replica's PARAMS (one bit-level uint32 per
  replica per period, through the transport) and votes: a strict-minority
  fingerprint is silent data corruption — the replica answers health
  probes OK while streaming plausibly-wrong tokens — and is fenced and
  re-homed exactly like a probe-death (``tokens_lost == 0``). Two
  replicas disagreeing is detected but unlocalized: recorded, never
  fenced.
* **Live join/drain** — :meth:`add_replica` warm-spawns a replica (AOT
  ``prewarm`` when a cache dir is given) and rebalances queued backlog
  onto it without pausing survivors; :meth:`remove_replica` drains one
  out through the DRAINING contract, re-homing its never-admitted queue.
* **Warm restart** — :meth:`restart_replica` snapshots a fenced replica's
  host-current serving state (``engine.snapshot_serving_state``), spawns
  a replacement, and restores the state there — the dead replica's queue
  is SURRENDERED to the snapshot first, so the halted-re-home path can
  never double-admit what the restore now owns.

* **One scrape** — replicas built by :meth:`ReplicaRouter.build` share one
  ``MetricsRegistry`` as engine-labeled metric families (the ISSUE 11
  machinery), so tenant/SLO attribution and the program/HBM ledgers of all
  replicas aggregate into a single Prometheus exposition with zero
  merging. A router built with ``registry=`` adds the fabric's own
  surface: the per-replica probe-state gauge, re-home / restart latency
  histograms, and the transport's send/retry/drop/dup counters.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from neuronx_distributed_tpu.inference.generate import GenerationConfig
from neuronx_distributed_tpu.serving.engine import (
    EngineHealth,
    RejectedError,
    ServingEngine,
)
from neuronx_distributed_tpu.serving.scheduler import Request
from neuronx_distributed_tpu.serving.transport import (
    InProcessTransport,
    TransportError,
)

# replicas mint rids from disjoint ranges so re-homed Request objects can
# never collide on a survivor (requests keep their rid across re-homing)
RID_STRIDE = 1_000_000_000

# probe-state ladder: demotions move right (one failure threshold each),
# recoveries move left one rung per `recover_after` consecutive successes
_PROBE_ORDER = ("ok", "suspect", "degraded", "dead")
_PROBE_CODE = {s: i for i, s in enumerate(_PROBE_ORDER)}


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """Watchdog probe cadence and state-machine thresholds.

    Probes fire every ``probe_interval_s`` (router clock — virtual under
    tests) with a per-message ``probe_timeout_s`` deadline; one probe is
    ONE transport attempt (no retries — retrying is what the consecutive-
    failure thresholds are for). ``suspect_after`` / ``degraded_after`` /
    ``dead_after`` are CUMULATIVE consecutive-failure counts;
    ``recover_after`` consecutive successes undo one demotion level.
    DEAD is terminal — the replica is fenced and its work re-homed."""

    probe_interval_s: float = 0.25
    probe_timeout_s: float = 0.25
    suspect_after: int = 1
    degraded_after: int = 2
    dead_after: int = 3
    recover_after: int = 2
    # integrity sentinel (ISSUE 20): every ``integrity_interval_s`` the
    # router fingerprints every routable replica's PARAMS through the
    # transport and votes — a strict-minority replica holds silently
    # corrupted weights (liveness probes see nothing wrong with it) and
    # is fenced + re-homed like a probe-death. None disables. Two-replica
    # disagreement detects but cannot localize: recorded, never fenced
    integrity_interval_s: Optional[float] = None

    def __post_init__(self):
        if not (1 <= self.suspect_after <= self.degraded_after
                <= self.dead_after):
            raise ValueError(
                "thresholds must satisfy 1 <= suspect_after <= "
                f"degraded_after <= dead_after, got {self.suspect_after}/"
                f"{self.degraded_after}/{self.dead_after}"
            )
        if self.recover_after < 1:
            raise ValueError(
                f"recover_after must be >= 1, got {self.recover_after}"
            )


@dataclasses.dataclass
class _ProbeState:
    """One replica's watchdog view (host scalars only)."""

    state: str = "ok"
    fails: int = 0  # consecutive probe failures
    oks: int = 0  # consecutive successes while demoted
    next_probe: float = 0.0
    last_err: Optional[str] = None


class ReplicaRouter:
    """Host-side request router over N ``ServingEngine`` replicas."""

    def __init__(self, replicas: List[ServingEngine],
                 affinity: bool = True,
                 affinity_overcommit: float = 0.85,
                 transport=None,
                 watchdog: Optional[WatchdogConfig] = None,
                 registry=None,
                 time_fn: Optional[Callable[[], float]] = None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        bases = [e._next_rid for e in replicas]
        if len(set(bases)) != len(bases):
            raise ValueError(
                "replicas must mint request ids from disjoint rid_base "
                "ranges (ServingEngine(rid_base=i * router.RID_STRIDE)) — "
                "re-homed requests keep their rid on the survivor"
            )
        self.replicas = list(replicas)
        self.affinity = affinity
        self.affinity_overcommit = float(affinity_overcommit)
        self._time_fn = time_fn
        self.transport = (
            transport if transport is not None
            else InProcessTransport(time_fn=self._now)
        )
        self.watchdog = watchdog
        self._probe: List[_ProbeState] = [
            _ProbeState() for _ in self.replicas
        ]
        self._dead: set = set()  # replica indices already drained/re-homed
        self._draining_out: set = set()  # remove_replica in progress
        # set by build(): how to construct another identical replica
        self._factory: Optional[dict] = None
        self._next_base = len(self.replicas)
        self.stats: Dict[str, int] = {
            "routed": 0,
            "affinity_hits": 0,
            "rehomed_requests": 0,
            "replicas_drained": 0,
            "spillovers": 0,
            # elastic fabric (ISSUE 18)
            "probes": 0,
            "probe_failures": 0,
            "watchdog_deaths": 0,
            "transport_failures": 0,
            "replicas_joined": 0,
            "replicas_removed": 0,
            "replicas_restarted": 0,
            "rebalanced_requests": 0,
            # integrity sentinel (ISSUE 20)
            "integrity_probes": 0,
            "integrity_fences": 0,
            "integrity_disagreements": 0,
        }
        self._next_integrity = 0.0
        self.routed_by_replica = [0] * len(replicas)
        # fabric observability (registry=): probe-state gauge children,
        # re-home / restart latency histograms, transport event gauges
        self._view = None
        self._g_probe = None
        self._g_transport = None
        self._h_rehome = None
        self._h_restart = None
        if registry is not None:
            from neuronx_distributed_tpu.observability.registry import (
                MetricsView,
            )

            self._view = MetricsView(registry)
            self._g_probe = self._view.family(
                "gauge", "router_probe_state",
                help="watchdog probe state per replica "
                     "(0=ok 1=suspect 2=degraded 3=dead)",
            )
            self._g_transport = self._view.family(
                "gauge", "router_transport_events",
                help="transport seam counters (messages/retries/drops/"
                     "dups/timeouts/partitions/dedup hits)",
            )
            self._h_rehome = self._view.histogram(
                "router_rehome_latency_s",
                help="wall time to re-home one dead replica's queue",
            )
            self._h_restart = self._view.histogram(
                "router_restart_latency_s",
                help="wall time of restart_replica (spawn + state restore)",
            )
            for i in range(len(self.replicas)):
                self._view.child(self._g_probe, f"replica{i}").set(0)

    def _now(self) -> float:
        if self._time_fn is not None:
            return self._time_fn()
        return self.replicas[0]._clock()

    # --- construction -------------------------------------------------------

    @classmethod
    def build(cls, model, params, n_replicas: int, registry=None,
              engine_label: str = "replica", transport=None,
              watchdog: Optional[WatchdogConfig] = None,
              **engine_kwargs) -> "ReplicaRouter":
        """Build N identically-configured replicas sharing ``params`` (one
        host copy — placement may still differ per mesh) and, when a
        ``registry`` is given, one labeled metrics registry
        (``{engine_label}{i}`` children) so all replicas scrape as one
        endpoint. A router built this way remembers the recipe, so
        :meth:`add_replica` / :meth:`restart_replica` can mint identical
        replicas live."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        replicas = []
        for i in range(n_replicas):
            kwargs = dict(engine_kwargs)
            if registry is not None:
                kwargs.setdefault("registry", registry)
                kwargs.setdefault("engine_label", f"{engine_label}{i}")
            replicas.append(
                ServingEngine(
                    model, params, rid_base=i * RID_STRIDE, **kwargs
                )
            )
        router = cls(replicas, transport=transport, watchdog=watchdog,
                     registry=registry)
        router._factory = {
            "model": model,
            "params": params,
            "engine_kwargs": dict(engine_kwargs),
            "registry": registry,
            "engine_label": engine_label,
        }
        return router

    def _spawn(self, **overrides) -> ServingEngine:
        """Construct one more replica from the build() recipe, minting
        from the next disjoint rid range."""
        if self._factory is None:
            raise ValueError(
                "this router was not built by ReplicaRouter.build() — "
                "pass an engine to add_replica() instead"
            )
        f = self._factory
        kwargs = dict(f["engine_kwargs"])
        kwargs.update(overrides)
        if f["registry"] is not None:
            kwargs.setdefault("registry", f["registry"])
            kwargs.setdefault(
                "engine_label", f"{f['engine_label']}{self._next_base}"
            )
        return ServingEngine(
            f["model"], f["params"],
            rid_base=self._next_base * RID_STRIDE, **kwargs
        )

    def add_replica(self, engine: Optional[ServingEngine] = None,
                    cache_dir: Optional[str] = None,
                    manifest=None, adopt_backlog: bool = True,
                    **overrides) -> int:
        """Join one replica LIVE — survivors keep stepping throughout.
        With no ``engine`` the router mints one from the build() recipe
        (``overrides`` update its kwargs — e.g. ``fault_injector=None``
        for a clean replacement). ``cache_dir`` warm-spawns: the new
        replica's programs come off the AOT cache (``engine.prewarm``)
        before it takes work. With ``adopt_backlog`` the router rebalances
        queued (never-admitted) requests from the most-loaded survivors
        onto the newcomer through the transport adopt path. Returns the
        new replica's index."""
        if engine is None:
            engine = self._spawn(**overrides)
        else:
            bases = {e._next_rid for e in self.replicas}
            if engine._next_rid in bases:
                raise ValueError(
                    "joining replica must mint from a disjoint rid_base "
                    "range (rid collisions break re-homing)"
                )
        if cache_dir is not None or manifest is not None:
            engine.prewarm(manifest=manifest, cache_dir=cache_dir)
        idx = len(self.replicas)
        self.replicas.append(engine)
        self.routed_by_replica.append(0)
        self._probe.append(_ProbeState(next_probe=self._now()))
        self._next_base += 1
        self.stats["replicas_joined"] += 1
        if self._view is not None:
            self._view.child(self._g_probe, f"replica{idx}").set(0)
        flight = getattr(engine, "flight", None)
        if flight is not None:
            flight.record("join", replica=idx)
        if adopt_backlog:
            self._rebalance_into(idx)
        return idx

    def _rebalance_into(self, new_idx: int) -> int:
        """Move queued never-admitted requests from the most-loaded
        accepting survivors onto a fresh replica until depths are within
        one of each other. Tail-first from each donor: older work keeps
        its position in the donor's queue."""
        moved = 0
        while True:
            new_depth = self.replicas[new_idx].scheduler.queued
            donors = [
                i for i in self._accepting()
                if i != new_idx
                and self.replicas[i].scheduler.queued > new_depth + 1
            ]
            if not donors:
                break
            donor_idx = max(
                donors, key=lambda i: self.replicas[i].scheduler.queued
            )
            donor = self.replicas[donor_idx]
            req = next(
                (r for r in reversed(donor.scheduler.queued_requests)
                 if r.admit_time is None),
                None,
            )
            if req is None:
                break
            got, cb = donor.release_queued(req.rid)
            if got is None:
                break
            self.transport.call(
                new_idx, "adopt",
                lambda r=got, c=cb: self.replicas[new_idx].adopt(
                    r, on_token=c
                ),
                rid=got.rid,
            )
            moved += 1
        self.stats["rebalanced_requests"] += moved
        return moved

    def remove_replica(self, idx: int,
                       rehome_queued: bool = True) -> ServingEngine:
        """Drain one replica OUT live: it stops receiving new work
        (DRAINING contract), its never-admitted queue re-homes to
        survivors (``rehome_queued``), its admitted in-flight work runs to
        completion, and ``step()`` retires it once idle. Returns the
        engine (still stepping until drained)."""
        if idx in self._dead or not (0 <= idx < len(self.replicas)):
            raise ValueError(f"replica {idx} is not live")
        e = self.replicas[idx]
        e.drain()
        self._draining_out.add(idx)
        if rehome_queued:
            for req in list(e.scheduler.queued_requests):
                if req.admit_time is not None:
                    continue  # admitted work finishes here per the contract
                targets = [t for t in self._accepting() if t != idx]
                if not targets:
                    break
                target = min(
                    targets,
                    key=lambda t: self.replicas[t].load_score(
                        tenant=req.tenant
                    ),
                )
                got, cb = e.release_queued(req.rid)
                if got is None:
                    continue
                self.transport.call(
                    target, "adopt",
                    lambda t=target, r=got, c=cb: self.replicas[t].adopt(
                        r, on_token=c
                    ),
                    rid=got.rid,
                )
                self.stats["rehomed_requests"] += 1
        flight = getattr(e, "flight", None)
        if flight is not None:
            flight.record("drain_out", replica=idx)
        return e

    def restart_replica(self, idx: int, cache_dir: Optional[str] = None,
                        manifest=None, **overrides) -> int:
        """Warm-restart a fenced/halted replica: snapshot its host-current
        serving state, SURRENDER its queue to the snapshot (so the
        halted-re-home path can never double-admit what the restore now
        owns), spawn a replacement off the build() recipe (AOT-prewarmed
        when ``cache_dir`` is given), and restore the state there — every
        request continues bit-identically with its remaining deadline
        budget and its streaming callback intact. Returns the replacement
        index.

        Raises ``ValueError`` if the replica's work was already re-homed
        (``step()`` or the watchdog got there first — spawn a fresh
        replica with :meth:`add_replica` instead)."""
        if idx in self._dead:
            raise ValueError(
                f"replica {idx} was already re-homed — its work lives on "
                "the survivors; use add_replica() for a fresh replacement"
            )
        if not (0 <= idx < len(self.replicas)):
            raise ValueError(f"replica {idx} does not exist")
        t0 = time.perf_counter()
        dead = self.replicas[idx]
        if dead.health() is not EngineHealth.HALTED:
            dead.fence("restart_replica")
        snap = dead.snapshot_serving_state()
        # surrender: the snapshot now owns this work — withdraw it (and
        # its callbacks) from the dead replica entirely
        callbacks = {}
        for req in list(dead.scheduler.queued_requests):
            dead.scheduler.release(req.rid)
            cb = dead._on_token.pop(req.rid, None)
            if cb is not None:
                callbacks[req.rid] = cb
        self._dead.add(idx)
        self._draining_out.discard(idx)
        if self._view is not None:
            self._view.child(self._g_probe, f"replica{idx}").set(
                _PROBE_CODE["dead"]
            )
        new_idx = self.add_replica(
            cache_dir=cache_dir, manifest=manifest, adopt_backlog=False,
            **overrides,
        )
        e = self.replicas[new_idx]
        report = self.transport.call(
            new_idx, "restore", lambda: e.restore_serving_state(snap)
        )
        for rid, cb in callbacks.items():
            e._on_token[rid] = cb
        self.stats["replicas_restarted"] += 1
        if self._h_restart is not None:
            self._h_restart.observe(time.perf_counter() - t0)
        flight = getattr(e, "flight", None)
        if flight is not None:
            flight.record(
                "restart", replica=idx, replacement=new_idx,
                restored=report["restored"],
            )
        return new_idx

    # --- routing ------------------------------------------------------------

    def _live(self) -> List[int]:
        return [i for i in range(len(self.replicas)) if i not in self._dead]

    def _accepting(self) -> List[int]:
        """Replica indices that may receive NEW work: OK first; DEGRADED
        only when no OK replica exists (drain-around); DRAINING/HALTED
        never. The watchdog's view composes in: probe-DEGRADED replicas
        drain around like engine-DEGRADED ones, probe-DEAD never accept.
        Probe-SUSPECT still accepts — demotion to SUSPECT is a note, not
        a verdict (that hysteresis is what keeps a flapping replica from
        bouncing in and out of rotation)."""
        ok, degraded = [], []
        for i in self._live():
            ps = self._probe[i].state
            if ps == "dead":
                continue
            h = self.replicas[i].health()
            if h is EngineHealth.OK and ps in ("ok", "suspect"):
                ok.append(i)
            elif h is EngineHealth.DEGRADED or ps == "degraded":
                if h not in (EngineHealth.DRAINING, EngineHealth.HALTED):
                    degraded.append(i)
        return ok if ok else degraded

    def _pick(self, prompt: np.ndarray,
              tenant: Optional[str] = None) -> List[int]:
        """Replica indices to try, best first. With a ``tenant`` the load
        score grows that tenant's attainment bias (ISSUE 16): under an
        SLO-scheduling replica set, a replica where this tenant's SLO is
        under water sorts later, so the tenant's next request lands where
        its SLO is healthiest. FIFO replicas bias 0.0 — order unchanged."""
        candidates = self._accepting()
        if not candidates:
            raise RejectedError(
                "no replica is accepting work (all draining/halted)",
                queue_depth=sum(
                    self.replicas[i].scheduler.queued for i in self._live()
                ),
            )
        order = sorted(
            candidates,
            key=lambda i: self.replicas[i].load_score(tenant=tenant),
        )
        if self.affinity:
            best_i, best_m = None, 0
            for i in candidates:
                e = self.replicas[i]
                if e.prefix is None:
                    continue
                if e.page_pressure() >= self.affinity_overcommit:
                    # the overcommit guard: a page-saturated replica's
                    # resident prefix is not worth living at its
                    # preemption wall — balance instead. Tiered replicas
                    # (ISSUE 19) clear this bar longer: page_pressure
                    # counts spill-reclaimable capacity, and a SPILLED
                    # prefix still matches below (the trie keeps
                    # host-resident entries), so affinity keeps steering
                    # at shared prefixes the host tier can serve
                    continue
                m = e.prefix.match_len(prompt)
                if m > best_m:
                    best_i, best_m = i, m
            if best_i is not None:
                self.stats["affinity_hits"] += 1
                order = [best_i] + [i for i in order if i != best_i]
        return order

    def submit(
        self,
        prompt_ids,
        config: GenerationConfig = GenerationConfig(),
        key=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        deadline_s: Optional[float] = None,
        queue_timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> Request:
        """Route one request to the best replica (affinity → health →
        load), spilling to the next-best on a bounded-queue rejection OR
        an unreachable replica (transport failure after retries); raises
        :class:`RejectedError` only when EVERY accepting replica
        refused."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        last_reject: Optional[RejectedError] = None
        for rank, i in enumerate(self._pick(prompt, tenant=tenant)):
            try:
                req = self.transport.call(
                    i, "submit",
                    lambda e=self.replicas[i]: e.submit(
                        prompt, config, key=key, on_token=on_token,
                        deadline_s=deadline_s,
                        queue_timeout_s=queue_timeout_s,
                        tenant=tenant, priority=priority,
                    ),
                )
            except RejectedError as e:
                last_reject = e
                if rank == 0:
                    self.stats["spillovers"] += 1
                continue
            except TransportError as e:
                self.stats["transport_failures"] += 1
                last_reject = RejectedError(
                    f"replica {i} unreachable: {e}",
                    queue_depth=self.replicas[i].scheduler.queued,
                )
                if rank == 0:
                    self.stats["spillovers"] += 1
                continue
            self.stats["routed"] += 1
            self.routed_by_replica[i] += 1
            return req
        assert last_reject is not None
        raise last_reject

    # --- watchdog (ISSUE 18) ------------------------------------------------

    def _probe_transition(self, i: int, ps: _ProbeState, new: str,
                          why: Optional[str]) -> None:
        was, ps.state = ps.state, new
        if self._view is not None:
            self._view.child(self._g_probe, f"replica{i}").set(
                _PROBE_CODE[new]
            )
        flight = getattr(self.replicas[i], "flight", None)
        if flight is not None:
            flight.record(
                "probe_state", replica=i, was=was, now=new,
                fails=ps.fails, why=why,
            )

    def _run_watchdog(self, now: float) -> None:
        cfg = self.watchdog
        for i in self._live():
            ps = self._probe[i]
            if ps.state == "dead" or now < ps.next_probe:
                continue
            ps.next_probe = now + cfg.probe_interval_s
            self.stats["probes"] += 1
            err = None
            try:
                h = self.transport.probe(
                    i, lambda e=self.replicas[i]: e.health().value,
                    deadline_s=cfg.probe_timeout_s,
                )
                if h == "halted":
                    err = "replica reports halted"
            except TransportError as e:
                err = f"{type(e).__name__}: {e}"
            if err is None:
                self._probe_success(i, ps)
            else:
                self._probe_failure(i, ps, err, now)

    def _probe_success(self, i: int, ps: _ProbeState) -> None:
        ps.fails = 0
        ps.last_err = None
        if ps.state == "ok":
            ps.oks = 0
            return
        ps.oks += 1
        if ps.oks >= self.watchdog.recover_after:
            # hysteresis: recovery is earned one level at a time, and the
            # success streak resets — a flapper stays demoted
            ps.oks = 0
            self._probe_transition(
                i, ps, _PROBE_ORDER[_PROBE_CODE[ps.state] - 1], "recovered"
            )

    def _probe_failure(self, i: int, ps: _ProbeState, err: str,
                       now: float) -> None:
        ps.fails += 1
        ps.oks = 0
        ps.last_err = err
        self.stats["probe_failures"] += 1
        cfg = self.watchdog
        if ps.fails >= cfg.dead_after:
            new = "dead"
        elif ps.fails >= cfg.degraded_after:
            new = "degraded"
        elif ps.fails >= cfg.suspect_after:
            new = "suspect"
        else:
            new = ps.state
        # demotion only — a failure never improves the state
        if _PROBE_CODE[new] > _PROBE_CODE[ps.state]:
            self._probe_transition(i, ps, new, err)
            if new == "dead":
                self._declare_dead(i, err)

    def _declare_dead(self, idx: int, err: str) -> None:
        """Probe-death: fence the replica (the in-process STONITH — a
        partitioned-but-alive replica must stop making progress before
        its work runs elsewhere, or two engines would stream one rid),
        then re-home its queue through the standard halt/adopt path."""
        self.stats["watchdog_deaths"] += 1
        ps = self._probe[idx]
        self.replicas[idx].fence(
            f"watchdog: {ps.fails} consecutive probe failures ({err})"
        )
        self._rehome(idx)

    def _run_integrity(self, now: float) -> None:
        """Periodic cross-replica PARAMS fingerprint vote (ISSUE 20) —
        the health-evidence source for corruption liveness probes cannot
        see: a replica with one flipped weight bit answers every health
        probe OK and keeps streaming plausibly-wrong tokens. Rides the
        watchdog's virtual clock and the transport (one uint32 readback
        per replica per period — never per chunk). A strict-minority
        fingerprint convicts its replica: fenced (STONITH) and re-homed
        through the same halt/adopt contract as a probe-death, so
        ``tokens_lost == 0``. No strict majority (two replicas
        disagreeing): detected, recorded, nobody fenced — fencing an
        innocent replica would be worse than routing around neither."""
        cfg = self.watchdog
        if cfg.integrity_interval_s is None or now < self._next_integrity:
            return
        self._next_integrity = now + cfg.integrity_interval_s
        values: Dict[int, int] = {}
        for i in self._live():
            if self._probe[i].state == "dead":
                continue
            self.stats["integrity_probes"] += 1
            try:
                values[i] = int(self.transport.probe(
                    i,
                    lambda e=self.replicas[i]: e.integrity_fingerprint(),
                    deadline_s=cfg.probe_timeout_s,
                ))
            except TransportError:
                # unreachable is the LIVENESS ladder's evidence, not
                # corruption evidence — the next _run_watchdog pass
                # handles it; the vote proceeds over who answered
                continue
        from neuronx_distributed_tpu.integrity.voting import vote

        verdict = vote(values)
        if verdict.clean:
            return
        if not verdict.localized:
            self.stats["integrity_disagreements"] += 1
            for i, v in verdict.values.items():
                flight = getattr(self.replicas[i], "flight", None)
                if flight is not None:
                    flight.record(
                        "integrity_disagreement", replica=i,
                        fingerprint=v, voters=len(verdict.values),
                    )
            return
        for i in verdict.convicted:
            self._declare_corrupt(
                i,
                f"params fingerprint {values[i]:#010x} vs quorum "
                f"{verdict.quorum_value:#010x} "
                f"({len(values) - 1} of {len(values)} replicas agree)",
            )

    def _declare_corrupt(self, idx: int, why: str) -> None:
        """Integrity conviction: straight to DEAD (no SUSPECT ladder —
        corrupted weights don't flap, and every chunk served meanwhile is
        wrong), fence, and re-home through the standard halt/adopt path."""
        self.stats["integrity_fences"] += 1
        ps = self._probe[idx]
        flight = getattr(self.replicas[idx], "flight", None)
        if flight is not None:
            flight.record("integrity_conviction", replica=idx, why=why)
        self._probe_transition(idx, ps, "dead", why)
        self.replicas[idx].fence(f"integrity: {why}")
        self._rehome(idx)

    # --- stepping / fault handling ------------------------------------------

    def _rehome(self, dead_idx: int) -> int:
        """Move a HALTED replica's queued work (requeued in-flight victims
        included — the engine's halt contract put them back with
        host-current tokens/keys) to survivors. Adopt rides the transport,
        so a duplicated or retried move still admits exactly once; a
        request whose adopt cannot be delivered stays queued on the dead
        replica for operator handoff (callback restored). Returns how many
        requests moved."""
        dead = self.replicas[dead_idx]
        t0 = time.perf_counter()
        moved = 0
        for req in list(dead.scheduler.queued_requests):
            targets = self._accepting()
            targets = [t for t in targets if t != dead_idx]
            if not targets:
                break
            target = min(
                targets,
                key=lambda i: self.replicas[i].load_score(
                    tenant=req.tenant
                ),
            )
            cb = dead._on_token.pop(req.rid, None)
            try:
                self.transport.call(
                    target, "adopt",
                    lambda t=target, r=req, c=cb: self.replicas[t].adopt(
                        r, on_token=c
                    ),
                    rid=req.rid,
                )
            except TransportError:
                self.stats["transport_failures"] += 1
                if cb is not None:
                    dead._on_token[req.rid] = cb
                continue
            moved += 1
        self._dead.add(dead_idx)
        self._draining_out.discard(dead_idx)
        self.stats["replicas_drained"] += 1
        self.stats["rehomed_requests"] += moved
        if self._h_rehome is not None:
            self._h_rehome.observe(time.perf_counter() - t0)
        flight = getattr(dead, "flight", None)
        if flight is not None:
            flight.record("rehome", replica=dead_idx, moved=moved)
        return moved

    def step(self) -> bool:
        """One router iteration: run the watchdog (probe-deaths fence and
        re-home inline), re-home any newly-halted replica's work, step
        every live replica that has work, and retire replicas that
        finished draining out. Returns whether work remains anywhere."""
        if self.watchdog is not None:
            now = self._now()
            self._run_watchdog(now)
            self._run_integrity(now)
        for i in self._live():
            if self.replicas[i].health() is EngineHealth.HALTED:
                self._rehome(i)
        for i in self._live():
            e = self.replicas[i]
            if e.has_work:
                e.step()
        for i in list(self._draining_out):
            if i not in self._dead and not self.replicas[i].has_work:
                self._dead.add(i)
                self._draining_out.discard(i)
                self.stats["replicas_removed"] += 1
                flight = getattr(self.replicas[i], "flight", None)
                if flight is not None:
                    flight.record("drained_out", replica=i)
        if self._view is not None:
            for k, v in self.transport.stats.items():
                self._view.child(self._g_transport, k).set(v)
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        """Step until no replica has work (or ``max_steps``); returns every
        request any replica has seen, by rid."""
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return self.requests

    # --- introspection ------------------------------------------------------

    @property
    def has_work(self) -> bool:
        for i in self._live():
            e = self.replicas[i]
            if e.has_work:
                return True
            # a halted replica makes no progress itself, but its requeued
            # work is the ROUTER's to move — run() must keep stepping
            # until the re-home happens
            if (
                e.health() is EngineHealth.HALTED
                and e.scheduler.queued > 0
            ):
                return True
        return False

    @property
    def requests(self) -> Dict[int, Request]:
        out: Dict[int, Request] = {}
        for e in self.replicas:
            out.update(e.scheduler.requests)
        return out

    def health(self) -> Dict[str, str]:
        """Per-replica health map plus the aggregate: ``ok`` while any
        replica accepts work, ``degraded`` when only DEGRADED replicas do,
        ``halted`` when none does."""
        per = {
            f"replica{i}": e.health().value
            for i, e in enumerate(self.replicas)
        }
        accepting = self._accepting() if self._live() else []
        if not accepting:
            agg = "halted"
        elif all(
            self.replicas[i].health() is EngineHealth.DEGRADED
            for i in accepting
        ):
            agg = "degraded"
        else:
            agg = "ok"
        return {"aggregate": agg, **per}

    def probe_states(self) -> Dict[str, str]:
        """The watchdog's per-replica verdicts (``ok`` everywhere when no
        watchdog is configured)."""
        return {
            f"replica{i}": self._probe[i].state
            for i in range(len(self.replicas))
        }

    def snapshot(self) -> dict:
        """Router bookkeeping + per-replica metrics snapshots (replicas
        built over one labeled registry ALSO aggregate into a single
        Prometheus scrape — this is the JSON view of the same data)."""
        return {
            "router": {
                **self.stats,
                "routed_by_replica": list(self.routed_by_replica),
                "health": self.health(),
                "probe_states": self.probe_states(),
                "transport": self.transport.snapshot(),
            },
            "replicas": {
                f"replica{i}": e.metrics.snapshot()
                for i, e in enumerate(self.replicas)
            },
        }

"""Continuous-batching serving engine.

``ServingEngine`` turns the repo's batch ``generate()`` math into a
request-level server: a host-side loop interleaves prefill of admitted
requests with ONE jitted fixed-shape decode program over all ``num_slots``
slots. The decode program's shapes never change — cache ``(num_slots,
max_seq_len)``, per-slot token/key/config arrays — so XLA compiles it exactly
once and every request, whatever its arrival time, length, or sampling
config, flows through the same program (``decode_compilations`` asserts
this). Prefill compiles once per padded-length bucket (powers of two,
``prefill_compilations`` / the bucket map bound it), the standard serving
trade.

Decode hot path — device-resident between admission events:

* All per-slot state (pending token, PRNG key, active mask, sampling
  sentinels, remaining-token budget, EOS id) lives ON DEVICE in
  ``self._state``; the jitted step updates it in place. The host writes a
  slot's row only when admission/free/cancel dirties it (one tiny jitted
  scatter per event), and reads a key back only at preemption/finish — the
  two places ``req.key`` is consumed.
* The KV cache and the slot-state dict are DONATED into the decode jit
  (``donate_argnums``): XLA aliases the buffers instead of copying the
  ``(num_slots, max_seq_len)`` cache pytree every token.
* ``decode_chunk_size`` (default 8; ``1`` reproduces a per-token loop)
  decode steps fuse into one jitted ``lax.scan``
  (:func:`~neuronx_distributed_tpu.inference.generate.chunked_decode_step`,
  shared with the one-shot ``generate`` module): per-slot EOS/budget
  freezing happens on device via the decode write mask, and the host pays
  ONE synchronization per chunk — a ``(chunk, num_slots)`` token block plus
  per-slot counts. Chunk boundaries are the admission/cancellation points,
  so larger chunks trade a little TTFT/cancel latency for per-token host
  overhead amortized ``chunk``-fold.

Token-stream fidelity: a request served through the engine produces EXACTLY
the tokens of a solo ``generate(prompt, key)`` call — same prefill math
(left-padded prompts are already proven token-identical to unpadded ones),
same per-step key evolution (``split`` then sample with the sub-key), and a
per-row sampler that is bit-identical to ``sample`` (utils/sampling.py) —
for every ``decode_chunk_size``, including across preemption/resume. The
engine is a scheduler around the same program, not a different generator.

Cache capacity: all slots share one write cursor (see
``serving/cache_manager.py``), which advances every decode step while ANY
slot is active. The fused chunk clamps itself against ``max_seq_len`` on
device and stops advancing once every slot froze, so the cursor lands
exactly where ``used`` single steps would have left it. Admission guards
against running past ``max_seq_len``:

* ``admission="conservative"`` (default) — admit only when the request's
  whole remaining generation fits under the cursor; requests queue
  otherwise, and the cursor rewinds whenever the engine drains.
* ``admission="eager"`` — admit whenever the prefill itself fits; when the
  cursor hits the wall the engine preempts every active request (their
  progress is kept), rewinds the cache, and resumes them by re-prefilling
  their context — trading re-prefill compute for slot utilization.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_tpu.inference.generate import (
    GenerationConfig,
    chunked_decode_step,
    serving_clones,
    validate_generate_args,
)
from neuronx_distributed_tpu.inference.utils import unwrap_logits
from neuronx_distributed_tpu.serving.cache_manager import SlotCacheManager
from neuronx_distributed_tpu.serving.metrics import ServingMetrics
from neuronx_distributed_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
)
from neuronx_distributed_tpu.utils.sampling import sample_row


def _key_data(key) -> np.ndarray:
    """Raw (2,) uint32 view of a PRNG key (typed or legacy)."""
    dt = getattr(key, "dtype", None)
    if dt is not None and jnp.issubdtype(dt, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    return np.asarray(key, np.uint32)


def _config_sentinels(cfg: GenerationConfig):
    """(temperature, top_k, top_p) with the traced-sampler sentinels:
    top_k<=0 and top_p>=1 disable the filters (sample_row's contract)."""
    return (
        np.float32(cfg.temperature),
        np.int32(cfg.top_k if cfg.top_k is not None else 0),
        np.float32(cfg.top_p if cfg.top_p is not None else 1.0),
    )


def _bucket(p: int, max_seq_len: int, remaining: int, floor: int = 8) -> int:
    """Padded prefill length for a p-token context: next power of two
    (compile-count control), clamped so the padded prompt still leaves room
    for the remaining generation — falling back to the exact length keeps
    every feasible request admittable at the cost of a per-length compile."""
    b = max(floor, 1 << max(p - 1, 0).bit_length())
    b = min(b, max_seq_len)
    if b < p or b + remaining > max_seq_len:
        b = p
    return b


def _slot_write(state, slot, tok, key, temp, topk, topp, remaining, eos):
    """One admission's device-side slot update. Every operand is a traced
    scalar/row, so slot churn reuses a single compiled program; jitted with
    the state donated — the update happens in place."""
    return dict(
        state,
        tok=state["tok"].at[slot].set(tok),
        keys=state["keys"].at[slot].set(key),
        active=state["active"].at[slot].set(True),
        temp=state["temp"].at[slot].set(temp),
        topk=state["topk"].at[slot].set(topk),
        topp=state["topp"].at[slot].set(topp),
        remaining=state["remaining"].at[slot].set(remaining),
        eos=state["eos"].at[slot].set(eos),
    )


def _slot_clear(state, slot):
    """Deactivate one slot on device (free/cancel); state donated."""
    return dict(state, active=state["active"].at[slot].set(False))


class ServingEngine:
    """Slot-based continuous batching over a mode-capable causal LM."""

    def __init__(
        self,
        model,
        params,
        num_slots: int,
        max_tokens_in_flight: Optional[int] = None,
        admission: str = "conservative",
        decode_chunk_size: int = 8,
        timeline=None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        if admission not in ("conservative", "eager"):
            raise ValueError(f"unknown admission policy {admission!r}")
        if decode_chunk_size < 1:
            raise ValueError(
                f"decode_chunk_size must be >= 1, got {decode_chunk_size}"
            )
        max_seq_len = getattr(getattr(model, "config", None), "max_seq_len", None)
        if max_seq_len is None:
            raise ValueError(
                "ServingEngine needs model.config.max_seq_len (the fixed "
                "slot cache length)"
            )
        self.model = model
        self.params = params  # property: binds self._params once per assign
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.admission = admission
        self.decode_chunk_size = decode_chunk_size
        self.timeline = timeline
        self._clock = time_fn
        self._prefill_model, self._decode_model = serving_clones(model)
        self.scheduler = Scheduler(max_tokens_in_flight)
        self.cache = SlotCacheManager(num_slots)
        self.metrics = ServingMetrics(num_slots)
        # host-side slot bookkeeping (scheduling only — the decode-visible
        # per-slot state lives on device in self._state)
        self._active = np.zeros((num_slots,), bool)
        self._slot_req: List[Optional[Request]] = [None] * num_slots
        self._on_token: Dict[int, Callable[[Request, int], None]] = {}
        self._next_rid = 0
        self._prefill_fns: Dict[int, Callable] = {}
        self._state = self._fresh_slot_state()
        # host snapshot of the per-slot keys from the CURRENT chunk readback
        # (set only while unpacking a chunk): finishing requests take their
        # key from here, so retirement costs no extra device sync
        self._chunk_keys: Optional[np.ndarray] = None
        # the fused decode chunk: cache AND slot state donated — XLA updates
        # both in place instead of materializing a fresh cache pytree
        self._decode_chunk = jax.jit(
            chunked_decode_step(
                self._decode_model, decode_chunk_size, max_seq_len
            ),
            donate_argnums=(1, 2),
        )
        self._slot_write = jax.jit(_slot_write, donate_argnums=(0,))
        self._slot_clear = jax.jit(_slot_clear, donate_argnums=(0,))
        self._first_token = jax.jit(sample_row)

    def _fresh_slot_state(self):
        b = self.num_slots
        return {
            "tok": jnp.zeros((b,), jnp.int32),
            "keys": jnp.zeros((b, 2), jnp.uint32),
            "active": jnp.zeros((b,), jnp.bool_),
            "temp": jnp.ones((b,), jnp.float32),
            "topk": jnp.zeros((b,), jnp.int32),
            "topp": jnp.ones((b,), jnp.float32),
            "remaining": jnp.zeros((b,), jnp.int32),
            "eos": jnp.full((b,), -1, jnp.int32),
        }

    # --- public API ---------------------------------------------------------

    @property
    def params(self):
        """The engine's weights. Assignment rebinds the params pytree the
        jitted prefill/decode programs receive — ONCE per assignment, not
        per step (the hot path never rebuilds it), so weight swaps still
        take effect on the next dispatch."""
        return self._params_src

    @params.setter
    def params(self, value):
        self._params_src = value
        self._params = dict(value)

    def submit(
        self,
        prompt_ids,
        config: GenerationConfig = GenerationConfig(),
        key=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
    ) -> Request:
        """Enqueue one request; returns its live ``Request`` (``tokens``
        fills in as the engine steps). ``key`` defaults to a per-request
        PRNGKey; pass the key you would give ``generate`` to reproduce its
        stream exactly."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if config.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # same capacity contract as generate(), checked by the shared helper
        validate_generate_args(
            self.model, prompt[None], config.max_new_tokens, None
        )
        budget = self.scheduler.max_tokens_in_flight
        if budget is not None and prompt.size + config.max_new_tokens > budget:
            # a footprint over the whole budget can NEVER be admitted —
            # queueing it would livelock run() behind a permanent FIFO head
            raise ValueError(
                f"request footprint ({prompt.size + config.max_new_tokens}) "
                f"exceeds max_tokens_in_flight ({budget}); it could never "
                "be admitted"
            )
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = jax.random.PRNGKey(rid)
        req = Request(
            rid=rid, prompt=prompt, config=config, key=_key_data(key)
        )
        req.submit_time = self._clock()
        if on_token is not None:
            self._on_token[rid] = on_token
        self.scheduler.submit(req)
        self.metrics.record_submit(req, req.submit_time)
        if self.timeline is not None:
            self.timeline.instant(f"submit r{rid}", "serving")
        return req

    def cancel(self, rid: int) -> bool:
        """Cancel a request. Queued: dropped immediately; running: its slot
        is reaped at the next chunk boundary."""
        req = self.scheduler.get(rid)
        if req is None or req.finished:
            return False
        was_queued = req.slot is None
        ok = self.scheduler.cancel(rid)
        if ok and was_queued:
            self.metrics.record_cancel(req, self._clock())
            # queued requests never reach _release_slot — drop the callback
            # here or it leaks for the engine's lifetime
            self._on_token.pop(rid, None)
        return ok

    @property
    def has_work(self) -> bool:
        return self.scheduler.queued > 0 or any(self._active)

    @property
    def decode_compilations(self) -> int:
        """How many distinct decode programs XLA compiled. Stays 1 across
        arbitrary slot churn — the continuous-batching invariant (one
        program per engine, whatever the chunk size)."""
        return int(self._decode_chunk._cache_size())

    @property
    def prefill_compilations(self) -> int:
        """How many distinct prefill programs XLA compiled — one per padded
        bucket length actually used, so growth is bounded by the number of
        distinct ``_bucket`` outputs (a handful of powers of two plus exact
        fallbacks)."""
        return sum(int(fn._cache_size()) for fn in self._prefill_fns.values())

    def step(self) -> bool:
        """One engine iteration: reap cancellations → preempt/rewind if the
        cursor is out of room → admit+prefill → one fused decode chunk →
        retire finished slots. Returns whether work remains."""
        now = self._clock()
        self._reap_cancelled(now)
        if any(self._active) and self.cache.cursor >= self.max_seq_len:
            self._preempt_all()
        if not any(self._active) and self.cache.cursor > 0:
            # drained: rewind the shared cursor so the next wave starts at
            # column 0 (storage reused, nothing reallocated)
            self.cache.reset()
        self._admit(now)
        if any(self._active):
            self._decode()
        if self.timeline is not None:
            self.timeline.counter("slots_active", int(self._active.sum()), "serving")
            self.timeline.counter("queue_depth", self.scheduler.queued, "serving")
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        """Step until idle; returns every request this engine has seen."""
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return {r.rid: r for r in self.scheduler.requests.values()}

    # --- admission ----------------------------------------------------------

    def _in_flight_tokens(self) -> int:
        return sum(
            r.token_footprint for r in self._slot_req if r is not None
        )

    def _admit(self, now: float) -> None:
        if self.cache.free_slots == 0 or self.scheduler.queued == 0:
            return
        proj = self.cache.cursor
        maxrem = max(
            (r.remaining_new_tokens for r in self._slot_req if r is not None),
            default=0,
        )

        def fits(req: Request) -> bool:
            nonlocal proj, maxrem
            p = len(req.context_ids)
            bucket = _bucket(p, self.max_seq_len, req.remaining_new_tokens)
            target = max(proj, bucket)
            if self.admission == "conservative":
                # all slots step together, so the cursor's final resting
                # place is the admission cursor plus the LONGEST remaining
                # generation in flight — a long prompt's cursor jump must
                # not strand the slots already running (they'd hit the
                # preemption wall conservative mode promises to avoid)
                if (
                    target + max(maxrem, req.remaining_new_tokens)
                    > self.max_seq_len
                ):
                    return False
            elif target + 1 > self.max_seq_len:
                # eager: just the prefill + one decode step must fit; the
                # preemption path recovers the rest
                return False
            proj = target
            maxrem = max(maxrem, req.remaining_new_tokens)
            return True

        selected = self.scheduler.select(
            self.cache.free_slots, self._in_flight_tokens(), fits
        )
        for req in selected:  # longest-prefill-first
            self._prefill_into_slot(req, self.cache.acquire(), now)

    def _prefill_fn(self, padded_len: int):
        fn = self._prefill_fns.get(padded_len)
        if fn is None:
            prefill = self._prefill_model

            @jax.jit
            def fn(params, ids, mask):
                out, variables = prefill.apply(
                    params, ids, padding_mask=mask, mutable=["cache"]
                )
                return unwrap_logits(out)[0, -1], variables["cache"]

            self._prefill_fns[padded_len] = fn
        return fn

    def _prefill_into_slot(self, req: Request, slot: int, now: float) -> None:
        ctx = req.context_ids
        p = len(ctx)
        padded = _bucket(p, self.max_seq_len, req.remaining_new_tokens)
        ids = np.zeros((1, padded), np.int32)
        mask = np.zeros((1, padded), bool)
        ids[0, padded - p:] = ctx  # LEFT padding: last token at index -1
        mask[0, padded - p:] = True
        if self.timeline is not None:
            self.timeline.mark_event_start("prefill", "serving")
        logits, row_cache = self._prefill_fn(padded)(
            self._params, jnp.asarray(ids), jnp.asarray(mask)
        )
        if self.timeline is not None:
            self.timeline.mark_event_end(
                "prefill", "serving", args={"rid": req.rid, "padded": padded}
            )
        self.cache.admit(row_cache, slot, padded)
        self.metrics.record_admit(req, now)
        if req.admit_time is None:
            req.admit_time = now
        if not req.tokens:
            # fresh request: sample the first token exactly as generate()
            # does — split the request key, sample with the sub-key
            carry, sub = jax.random.split(jnp.asarray(req.key))
            temp, topk, topp = _config_sentinels(req.config)
            tok0 = int(self._first_token(logits, sub, temp, topk, topp))
            req.key = np.asarray(carry, np.uint32)
            self._emit_token(req, tok0, now, first=True)
            if req.state is RequestState.CANCELLED:
                # the on_token callback cancelled on the FIRST token (while
                # req.slot was still None, so cancel() already recorded it):
                # the slot was acquired but never bound — free it and stop
                # before the DECODE transition would erase the cancellation
                req.finish_time = now
                self.cache.free(slot)
                self._on_token.pop(req.rid, None)
                return
        req.state = RequestState.DECODE
        req.slot = slot
        self._slot_req[slot] = req
        temp, topk, topp = _config_sentinels(req.config)
        self._state = self._slot_write(
            self._state,
            np.int32(slot),
            np.int32(req.tokens[-1]),
            jnp.asarray(req.key),
            temp, topk, topp,
            np.int32(req.remaining_new_tokens),
            np.int32(
                req.config.eos_token_id
                if req.config.eos_token_id is not None
                else -1
            ),
        )
        self._active[slot] = True
        # a request can be born finished (max_new_tokens == 1, or EOS as
        # its very first token) — retire before it ever decodes
        self._maybe_finish(req, now)

    # --- decode -------------------------------------------------------------

    def _decode(self) -> None:
        """One fused decode chunk: dispatch the donated jitted scan, then a
        SINGLE host synchronization for the whole token block. Between here
        and the next admission/free event no per-slot host state moves."""
        tl = self.timeline
        active_at_dispatch = int(self._active.sum())
        if tl is not None:
            tl.mark_event_start("decode_dispatch", "serving")
        t0 = self._clock()
        cache_in = self.cache.take()
        try:
            (new_cache, self._state, toks, counts, used,
             key_snap) = self._decode_chunk(
                self._params, cache_in, self._state
            )
        except BaseException:
            # a failed dispatch must not leave the manager cache-less: a
            # later admission would silently reallocate zeros under
            # still-active slots. Restored buffers that WERE consumed fail
            # loudly (deleted-buffer error) on next use instead.
            self.cache.restore(cache_in)
            raise
        t1 = self._clock()
        if tl is not None:
            tl.mark_event_end("decode_dispatch", "serving")
            tl.mark_event_start("decode_readback", "serving")
        # THE one host sync per chunk: the (chunk, slots) token block, the
        # per-slot valid-prefix lengths, the executed step count — and the
        # post-chunk key SNAPSHOT (frozen at each slot's finish step), so
        # requests retiring this chunk need no per-slot key pull. The
        # snapshot is a chunk OUTPUT, not the state leaf: device_get on the
        # leaf would cache a host value on it and silently demote the next
        # chunk's keys donation to a copy
        toks, counts, used, chunk_keys = jax.device_get(
            (toks, counts, used, key_snap)
        )
        t2 = self._clock()
        used = int(used)
        emitted = int(counts.sum())
        self.cache.update_after_decode(new_cache, used)
        if tl is not None:
            tl.mark_event_end(
                "decode_readback", "serving",
                args={"tokens": emitted, "steps": used},
            )
        now = self._clock()
        delivered = 0
        self._chunk_keys = chunk_keys
        try:
            for slot in np.flatnonzero(self._active):
                req = self._slot_req[slot]
                for tok in toks[: int(counts[slot]), slot]:
                    self._emit_token(req, int(tok), now)
                    delivered += 1
                    self._maybe_finish(req, now)
                    if req.finished:
                        # EOS/budget retired it, or an on_token callback
                        # cancelled it: discard the rest of its block
                        break
        finally:
            self._chunk_keys = None
        # recorded after the unpack so a mid-chunk cancellation's discarded
        # device tokens never inflate decode_tokens / chunk tok/s
        if tl is not None:
            tl.counter("chunk_tokens", delivered, "serving")
        self.metrics.record_decode_chunk(
            delivered, used, self.cache.cursor, active_at_dispatch,
            dispatch_s=t1 - t0, readback_s=t2 - t1,
        )

    # --- lifecycle helpers --------------------------------------------------

    def _pull_key(self, slot: int) -> np.ndarray:
        """Per-slot device→host key fetch — used only at PREEMPTION (the
        one place a key must leave the device outside a chunk readback;
        finishing requests take theirs from the chunk's own sync via
        ``_chunk_keys``). The chunked step freezes a finished slot's key at
        its last sampled token, so both paths yield exactly the
        single-step value."""
        return np.array(jax.device_get(self._state["keys"][slot]), np.uint32)

    def _emit_token(self, req: Request, tok: int, now: float,
                    first: bool = False) -> None:
        req.tokens.append(tok)
        if first:
            req.first_token_time = now
            self.metrics.record_first_token(req, now)
        cb = self._on_token.get(req.rid)
        if cb is not None:
            cb(req, tok)

    def _maybe_finish(self, req: Request, now: float) -> None:
        if req.state is RequestState.CANCELLED:
            # e.g. an on_token callback cancelled it this very step: the
            # cancellation wins; _reap_cancelled retires the slot next step
            return
        eos = req.config.eos_token_id
        hit_eos = eos is not None and req.tokens and req.tokens[-1] == eos
        if hit_eos or len(req.tokens) >= req.config.max_new_tokens:
            req.state = RequestState.DONE
            req.finish_time = now
            if req.slot is not None and self._chunk_keys is not None:
                # retiring mid-unpack: the post-chunk key already rode the
                # chunk's single readback (at prefill-time finishes req.key
                # is current on the host and needs no update)
                req.key = np.array(self._chunk_keys[req.slot], np.uint32)
            self.metrics.record_finish(req, now)
            self._release_slot(req)
            if self.timeline is not None:
                self.timeline.instant(f"done r{req.rid}", "serving")

    def _release_slot(self, req: Request) -> None:
        slot = req.slot
        if slot is None:
            return
        req.slot = None
        self._slot_req[slot] = None
        self._active[slot] = False
        self._state = self._slot_clear(self._state, np.int32(slot))
        self.cache.free(slot)
        self._on_token.pop(req.rid, None)

    def _reap_cancelled(self, now: float) -> None:
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.state is RequestState.CANCELLED:
                self.metrics.record_cancel(req, now)
                req.finish_time = now
                self._release_slot(req)

    def _preempt_all(self) -> None:
        """Out of cache columns: push every active request back to the queue
        (keeping its generated tokens and its device-held key), rewind the
        cache, and let admission re-prefill their contexts. Token streams
        are unaffected — resume replays the exact context the request had."""
        preempted = [r for r in self._slot_req if r is not None]
        for req in preempted:
            req.preemptions += 1
            self.metrics.record_preemption(req)
            req.key = self._pull_key(req.slot)
            slot, req.slot = req.slot, None
            self._slot_req[slot] = None
            self._active[slot] = False
        self.scheduler.requeue_front(preempted)
        # ONE device reset invalidates every row — per-slot free() dispatches
        # here would be N redundant full-cache programs; only the host
        # free-list needs per-slot bookkeeping
        self.cache.release_all_slots()
        self.cache.reset()
        # every slot is empty now; re-admission re-uploads each row, so a
        # fresh zero state is cheaper than N per-slot clears
        self._state = self._fresh_slot_state()
        if self.timeline is not None:
            self.timeline.instant(
                f"preempt x{len(preempted)}", "serving"
            )

"""Continuous-batching serving engine.

``ServingEngine`` turns the repo's batch ``generate()`` math into a
request-level server: a host-side loop interleaves prefill of admitted
requests with ONE jitted fixed-shape decode program over all ``num_slots``
slots. The decode program's shapes never change — cache ``(num_slots,
max_seq_len)``, per-slot token/key/config arrays — so XLA compiles it exactly
once and every request, whatever its arrival time, length, or sampling
config, flows through the same program (``decode_compilations`` asserts
this). Prefill compiles once per padded-length bucket (powers of two,
``prefill_compilations`` / the bucket map bound it), the standard serving
trade.

Decode hot path — device-resident between admission events:

* All per-slot state (pending token, PRNG key, active mask, sampling
  sentinels, remaining-token budget, EOS id) lives ON DEVICE in
  ``self._state``; the jitted step updates it in place. The host writes a
  slot's row only when admission/free/cancel dirties it (one tiny jitted
  scatter per event). Each chunk's single readback carries a COPIED
  post-chunk key snapshot, and the engine mirrors it into every active
  request's ``req.key`` — so preemption, recovery, and retirement all run
  off host state, with zero extra device syncs (``device_get`` on the state
  leaf itself would cache a host value and silently demote the next chunk's
  donation to a copy — regression-tested).
* The KV cache and the slot-state dict are DONATED into the decode jit
  (``donate_argnums``): XLA aliases the buffers instead of copying the
  ``(num_slots, max_seq_len)`` cache pytree every token.
* ``decode_chunk_size`` (default 8; ``1`` reproduces a per-token loop)
  decode steps fuse into one jitted ``lax.scan``
  (:func:`~neuronx_distributed_tpu.inference.generate.chunked_decode_step`,
  shared with the one-shot ``generate`` module): per-slot EOS/budget
  freezing happens on device via the decode write mask, and the host pays
  ONE synchronization per chunk — a ``(chunk, num_slots)`` token block plus
  per-slot counts. Chunk boundaries are the admission/cancellation points,
  so larger chunks trade a little TTFT/cancel latency for per-token host
  overhead amortized ``chunk``-fold.

Prefix-cache KV reuse — the admission-path optimization for shared-prompt
traffic (system prompts, few-shot templates, multi-turn histories):

* A host-managed :class:`~neuronx_distributed_tpu.serving.cache_manager.
  PrefixCache` (token trie, ref-counted entries, LRU eviction) stores
  compact COPIES of previously-prefilled context KV. On admission the
  engine looks up the longest stored prefix of the incoming context
  (capped at ``context - 1``), validates the entry (shape + fingerprint —
  a corrupted entry is evicted and the admission falls back to the full
  prefill), seeds a fresh cache row from it, and prefills ONLY the
  uncached tail through the decode-mode cache-write path at an explicit
  start cursor (``suffix_prefill_step``): QKV/MLP compute for ``s``
  suffix tokens instead of the whole prompt. Misses insert the admitted
  context so the next shared-prefix request hits.
* Alignment is the load-bearing invariant: the seeded row reproduces the
  exact left-padded layout a full prefill of the same context would build
  (prefix at ``[padded - p, padded - s)``, suffix written at
  ``padded - s``, RoPE continuing at the prefix's valid count), so slot
  roll-in, cursor arithmetic, and every later decode step are unchanged —
  token streams stay bit-identical to the cache-off path across hit /
  miss / partial-match / eviction / preemption-resume patterns.
* Donation safety: entries are extracted as fresh copies BEFORE the row
  enters the donating admit program and are pinned (ref-counted) while a
  suffix prefill is in flight — eviction never frees an entry backing an
  admission, and no stored buffer is ever aliased into a donated pytree.
  A weight swap (``engine.params = ...``) clears the store (old-weight KV
  must not serve new-weight traffic); recovery/halt drop any in-flight
  pins. ``prefix_cache=None`` (or size 0) disables everything and
  restores the exact legacy admission path.

Speculative decoding (ISSUE 9) — the decode path's multi-token transport:

* With ``draft_model=``/``draft_params=`` bound, each decode chunk runs
  ``decode_chunk_size`` fused draft–verify ROUNDS
  (:func:`~neuronx_distributed_tpu.inference.spec_decode.
  speculative_decode_chunk`): the draft proposes ``gamma`` tokens through
  its own donated cache, the target verifies the window in one forward,
  and each slot accepts its own longest matching prefix + correction —
  1..gamma tokens per slot per round, still ONE ``device_get`` per chunk.
* Per-slot variable advance rides the validity machinery: both caches
  write each round's window at the shared cursor, acceptance invalidates
  each row's rejected suffix, and per-row valid counts make the gap
  columns invisible — one fixed-shape program for every acceptance
  pattern (``decode_compilations`` stays 1).
* The draft side mirrors the slot lifecycle 1:1: a second
  ``SlotCacheManager`` (admit at the same cursor, free/quarantine/
  recover/reset in lockstep), per-bucket draft prefill programs (the
  draft always full-prefills — prefix-cache hits compose on the target
  side only), and the same donation regime.
* Greedy streams are bit-identical to the spec-off engine and to solo
  ``generate()``/``speculative_generate``; sampled slots emit one
  exactly-sampled token per round (same key evolution), also
  bit-identical. ``draft_model=None`` is byte-for-byte today's engine.
* A failed speculative dispatch with live buffers decodes that chunk
  non-speculatively (the exact spec-off program — zero tokens lost),
  then preempts to resync the draft cache; consumed buffers take the
  bounded recovery/HALT path. Speculation consumes ``gamma`` columns per
  round whatever it accepts, so poor acceptance reaches the
  preempt-and-rewind wall earlier — admission stays token-optimistic and
  preemption keeps streams exact.

Token-stream fidelity: a request served through the engine produces EXACTLY
the tokens of a solo ``generate(prompt, key)`` call — same prefill math
(left-padded prompts are already proven token-identical to unpadded ones),
same per-step key evolution (``split`` then sample with the sub-key), and a
per-row sampler that is bit-identical to ``sample`` (utils/sampling.py) —
for every ``decode_chunk_size``, including across preemption/resume AND
across dispatch-failure recovery. The engine is a scheduler around the same
program, not a different generator.

Fault tolerance — the contract that makes the donated hot path safe to run
unattended (chaos-tested in ``tests/serving/test_faults.py``):

* **Deadlines & shedding** — ``submit(..., deadline_s=, queue_timeout_s=)``
  attaches absolute deadlines; queue-expired requests are shed (terminal
  ``TIMED_OUT``) before prefill ever runs, and in-flight deadlines are
  enforced at chunk boundaries (the natural host-visibility points of the
  fused decode path). A shed request keeps the tokens it already streamed.
* **Dispatch recovery** — a failed donated decode dispatch no longer
  crashes the host loop: the engine restores/salvages the cache, requeues
  every in-flight request through the preemption machinery (their streams
  resume bit-identically — tokens and keys are host-current at every chunk
  boundary), waits per the shared decrementing-jitter
  :class:`~neuronx_distributed_tpu.utils.retry.RetryPolicy`, and retries on
  the next step. ``dispatch_retry.max_attempts`` CONSECUTIVE failures land
  the engine in ``HALTED`` with the work requeued, not lost.
* **Output validation & quarantine** — the per-chunk readback is validated
  on host (vocab-range tokens, sane counts); a poisoned slot is quarantined
  out of the rotation permanently, its request requeued from the last chunk
  boundary (or failed, under ``quarantine_policy="fail"``), and its
  neighbors' streams are untouched. Losing slots degrades capacity
  (``DEGRADED``); losing all of them halts.
* **Backpressure, drain & health** — ``max_queue`` bounds the queue with an
  explicit :class:`RejectedError` (carrying the depth), ``drain()`` stops
  admission while finishing in-flight work, and ``health()`` reports
  ``OK/DEGRADED/DRAINING/HALTED``; every fault shows up in
  ``metrics.snapshot()`` (sheds/rejects/quarantines/dispatch_retries/
  recoveries/health) and as Timeline instant events.
* **Fault injection** — every recovery path above is drivable
  deterministically through a
  :class:`~neuronx_distributed_tpu.serving.faults.FaultInjector` hook
  (dispatch raise at attempt k, poisoned readback for slot s, prefill
  OOM-like error, clock skew); with no injector the hooks are no-ops.

Observability (ISSUE 8 — zero syncs added; the pinned budgets above hold
with everything enabled):

* Metrics live in a shared ``MetricsRegistry`` (``metrics.registry``,
  injectable via ``registry=``) with log-bucketed TTFT/TPOT/prefill
  histograms and Prometheus/JSON export.
* With a ``timeline``, every request emits a connected Perfetto flow
  (submit → admission → prefix lookup → prefill → first token → decode
  chunks → retire/shed/quarantine/recovery) via ``self.tracer``; the
  timeline auto-saves (atomically) on halt.
* ``self.flight`` (a ``FlightRecorder``, ``flight_dir=`` for the dump
  location) records health transitions and fault events and writes a
  redacted JSON post-mortem the moment the engine HALTs — including, under
  multi-tenant load, per-tenant queue depths and the SLO attainment state
  (who was being starved when it died).
* ``profile_dir=`` captures a ``jax.profiler`` device trace of decode
  chunks [2, 5).
* Device efficiency (ISSUE 12): every jitted program the engine (and its
  cache/paging managers) dispatches registers in ``self.programs`` — a
  :class:`~neuronx_distributed_tpu.observability.programs.ProgramLedger`
  recording dispatch counts, compile wall, compiler-reported FLOPs/bytes
  (cost analysis runs lazily at export, never on the hot path) and
  per-chunk roofline telemetry off the walls the loop already measures;
  ``self.hbm`` (:class:`~neuronx_distributed_tpu.observability.hbm.
  HBMLedger`) reconciles the engine's static residents (params, KV pool,
  draft cache, slot state, prefix store) against device limits and
  answers capacity questions (``hbm.plan()``). Both ride
  ``metrics.snapshot()["programs"]``/``["hbm"]`` and the halt
  post-mortem; backend gaps degrade to explicit ``"unavailable"``.
* SLO observability (ISSUE 11): ``submit(..., tenant=, priority=)``
  attributes every request (per-tenant TTFT/TPOT/queue-wait histogram
  families, shed/timeout/reject counters, tenant-tagged flows and flight
  events); ``slo=`` (``SLOSpec`` or ``{tenant: SLOSpec}``) classifies each
  request once at its terminal state and reports attainment + goodput per
  tenant; ``engine_label=`` lets N engines share one registry as labeled
  families. ``serving/traffic.py`` replays seeded multi-tenant load
  through the engine on a virtual clock for reproducible SLO reports.

Multi-chip serving (ISSUE 14): ``tp=``/``mesh=`` shards this whole engine
over a TP mesh — params by their ``nn.Partitioned`` axis rules (the T5X
partitioner pattern, ``parallel/sharding.py``), KV storage on the kv-head
axis, slot state replicated — with every guarantee above intact: one
decode program, the same host-sync budgets (the chunk readback is
replicated scalars/tokens, never sharded KV), and streams bit-identical
to the mesh-free engine. ``tp_comms=`` optionally routes the row-parallel
all-reduces through the EQuARX int8 ring; ``paged_attention="fused"``
streams paged attention straight off the pool pages on TPU. N engines
scale out behind ``serving/router.py``'s ReplicaRouter, and
``serving/disagg.py`` splits prefill from decode with zero-copy
page-table handoffs (``admit_staged``).

Cache capacity: all slots share one write cursor (see
``serving/cache_manager.py``), which advances every decode step while ANY
slot is active. The fused chunk clamps itself against ``max_seq_len`` on
device and stops advancing once every slot froze, so the cursor lands
exactly where ``used`` single steps would have left it. Admission guards
against running past ``max_seq_len``:

* ``admission="conservative"`` (default) — admit only when the request's
  whole remaining generation fits under the cursor; requests queue
  otherwise, and the cursor rewinds whenever the engine drains.
* ``admission="eager"`` — admit whenever the prefill itself fits; when the
  cursor hits the wall the engine preempts every active request (their
  progress is kept), rewinds the cache, and resumes them by re-prefilling
  their context — trading re-prefill compute for slot utilization.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import re
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_tpu.inference.generate import (
    GenerationConfig,
    chunked_decode_step,
    pack_padded_prompt,
    serving_clones,
    suffix_prefill_step,
    validate_generate_args,
)
from neuronx_distributed_tpu.inference.spec_decode import (
    speculative_decode_chunk,
)
from neuronx_distributed_tpu.inference.utils import unwrap_logits
from neuronx_distributed_tpu.modules.attention import (
    cache_fingerprint,
    extract_cache_prefix,
    seed_cache_prefix,
)
from neuronx_distributed_tpu.observability.flight_recorder import FlightRecorder
from neuronx_distributed_tpu.observability.hbm import HBMLedger, tree_nbytes
from neuronx_distributed_tpu.observability.programs import (
    ProgramLedger,
    per_instance,
    weak_reader,
)
from neuronx_distributed_tpu.observability.tracing import RequestTracer
from neuronx_distributed_tpu.serving.cache_manager import (
    PrefixCache,
    SlotCacheManager,
)
from neuronx_distributed_tpu.serving.paging import (
    PagedCacheManager,
    PageExhausted,
)
from neuronx_distributed_tpu.serving.tiering import HostPageStore
from neuronx_distributed_tpu.serving.metrics import ServingMetrics
from neuronx_distributed_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
)
from neuronx_distributed_tpu.utils.retry import RetryPolicy
from neuronx_distributed_tpu.utils.sampling import sample_row


class EngineHealth(enum.Enum):
    """Engine health snapshot (``ServingEngine.health()``).

    ``OK`` — serving normally. ``DEGRADED`` — serving, but a recent
    dispatch failure was recovered from or quarantines have shrunk slot
    capacity. ``DRAINING`` — finishing in-flight work, admitting nothing
    new. ``HALTED`` — consecutive dispatch failures (or total slot loss)
    exhausted the retry budget; in-flight work is requeued and the loop
    stops making progress until an operator intervenes."""

    OK = "ok"
    DEGRADED = "degraded"
    DRAINING = "draining"
    HALTED = "halted"


class RejectedError(RuntimeError):
    """A submission the engine refused (bounded queue backpressure, drain,
    or halt). ``queue_depth`` is the queue occupancy at rejection time so
    callers can implement load-aware retry/spillover."""

    def __init__(self, message: str, queue_depth: int = 0):
        super().__init__(message)
        self.queue_depth = queue_depth


def _key_data(key) -> np.ndarray:
    """Raw (2,) uint32 view of a PRNG key (typed or legacy)."""
    dt = getattr(key, "dtype", None)
    if dt is not None and jnp.issubdtype(dt, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    if isinstance(key, jax.Array):
        # graftlint: ok[GL02] submit-time key capture — one tiny read on
        # the admission API, not the decode loop; explicit so a
        # transfer-guarded run can tell it from an accidental sync
        key = jax.device_get(key)
    return np.asarray(key, np.uint32)


def _config_sentinels(cfg: GenerationConfig):
    """(temperature, top_k, top_p) with the traced-sampler sentinels:
    top_k<=0 and top_p>=1 disable the filters (sample_row's contract)."""
    return (
        np.float32(cfg.temperature),
        np.int32(cfg.top_k if cfg.top_k is not None else 0),
        np.float32(cfg.top_p if cfg.top_p is not None else 1.0),
    )


def _bucket(p: int, max_seq_len: int, remaining: int, floor: int = 8) -> int:
    """Padded prefill length for a p-token context: next power of two
    (compile-count control), clamped so the padded prompt still leaves room
    for the remaining generation — falling back to the exact length keeps
    every feasible request admittable at the cost of a per-length compile."""
    b = max(floor, 1 << max(p - 1, 0).bit_length())
    b = min(b, max_seq_len)
    if b < p or b + remaining > max_seq_len:
        b = p
    return b


def _suffix_bucket(s: int, padded: int, max_seq_len: int) -> int:
    """Padded chunk length for an s-token suffix prefill: next power of two
    (one compiled suffix program per chunk bucket), falling back to the
    exact length whenever the padded chunk's writes — which start at
    ``padded - s``, the reused prefix's end — would run past the cache row
    (a clamped ``dynamic_update_slice`` would silently shift them onto the
    prefix). This is the reused-token side of the admission fits
    arithmetic: the prefix occupies its columns for free, so only the
    suffix chunk needs write room."""
    b = max(1, 1 << max(s - 1, 0).bit_length())
    if padded - s + b > max_seq_len:
        b = s
    return b


def _prefix_bucket(p: int, max_seq_len: int) -> int:
    """Storage bucket for a p-token prefix entry: next power of two clamped
    to the cache length (one compiled extract program per bucket)."""
    return min(max(1, 1 << max(p - 1, 0).bit_length()), max_seq_len)


def _validate_readback(toks, counts, chunk_size: int, vocab: Optional[int],
                       slots) -> Dict[int, str]:
    """Host-side sanity check of a chunk readback — the one-per-chunk sync
    is the only place device output is visible, so it is where a poisoned
    slot must be caught before its garbage reaches a stream. Returns
    ``{slot: reason}`` for every active slot whose token column fails the
    invariants (count within [0, chunk], token ids within [0, vocab))."""
    bad: Dict[int, str] = {}
    for slot in slots:
        slot = int(slot)
        c = int(counts[slot])
        if c < 0 or c > chunk_size:
            bad[slot] = f"token count {c} outside [0, {chunk_size}]"
            continue
        if c == 0:
            continue
        col = np.asarray(toks[:c, slot])
        if (col < 0).any() or (vocab is not None and (col >= vocab).any()):
            offender = col[
                (col < 0) | ((col >= vocab) if vocab is not None else False)
            ][0]
            bad[slot] = (
                f"token {int(offender)} outside vocab [0, {vocab})"
            )
    return bad


def _validate_spec_readback(toks, counts, gamma: int, vocab: Optional[int],
                            slots) -> Dict[int, str]:
    """Speculative edition of :func:`_validate_readback`: the token block
    is ``(rounds, slots, gamma)`` ragged by per-round counts. A slot is
    poisoned when any round's count leaves [0, gamma] or any EMITTED token
    leaves the vocab."""
    bad: Dict[int, str] = {}
    rounds = counts.shape[0]
    for slot in slots:
        slot = int(slot)
        for r in range(rounds):
            c = int(counts[r, slot])
            if c < 0 or c > gamma:
                bad[slot] = (
                    f"round {r} token count {c} outside [0, {gamma}]"
                )
                break
            if c == 0:
                continue
            col = np.asarray(toks[r, slot, :c])
            if (col < 0).any() or (
                vocab is not None and (col >= vocab).any()
            ):
                offender = col[
                    (col < 0) | ((col >= vocab) if vocab is not None else False)
                ][0]
                bad[slot] = (
                    f"round {r} token {int(offender)} outside vocab "
                    f"[0, {vocab})"
                )
                break
    return bad


def _slot_write(state, slot, tok, key, temp, topk, topp, remaining, eos):
    """One admission's device-side slot update. Every operand is a traced
    scalar/row, so slot churn reuses a single compiled program; jitted with
    the state donated — the update happens in place."""
    return dict(
        state,
        tok=state["tok"].at[slot].set(tok),
        keys=state["keys"].at[slot].set(key),
        active=state["active"].at[slot].set(True),
        temp=state["temp"].at[slot].set(temp),
        topk=state["topk"].at[slot].set(topk),
        topp=state["topp"].at[slot].set(topp),
        remaining=state["remaining"].at[slot].set(remaining),
        eos=state["eos"].at[slot].set(eos),
    )


def _slot_clear(state, slot):
    """Deactivate one slot on device (free/cancel); state donated."""
    return dict(state, active=state["active"].at[slot].set(False))


class _TraceScope:
    """Forwarding wrapper entering a context manager around every call of
    a jitted program — the program's (lazy) TRACE then happens inside the
    scope, which is how the engine's ``tp_comms`` config reaches the
    row-parallel layers without global state leaking between engines.
    Attribute reads (``_cache_size``, ``lower``, ``last_call_compiled``)
    forward to the wrapped callable so every compile-count property and
    ledger proxy keeps working unchanged."""

    def __init__(self, fn, make_ctx):
        self._fn = fn
        self._make_ctx = make_ctx

    def __call__(self, *args, **kwargs):
        with self._make_ctx():
            return self._fn(*args, **kwargs)

    def lower(self, *args, **kwargs):
        # lowering must enter the SAME scope as dispatch: a lower() outside
        # it traces the exact-psum program even on a tp_comms engine, so
        # every IR-level consumer (ledger cost analysis, graftverify's
        # donation/collective checks) would verify a program the engine
        # never runs — the trace-scope-leakage class GL07 encodes
        with self._make_ctx():
            return self._fn.lower(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class ServingEngine:
    """Slot-based continuous batching over a mode-capable causal LM."""

    def __init__(
        self,
        model,
        params,
        num_slots: int,
        max_tokens_in_flight: Optional[int] = None,
        admission: str = "conservative",
        scheduling="fifo",
        decode_chunk_size: int = 8,
        max_queue: Optional[int] = None,
        draft_model=None,
        draft_params=None,
        gamma: int = 4,
        kv_page_size: Optional[int] = None,
        kv_num_pages: Optional[int] = None,
        kv_host_pages: Optional[int] = None,
        quantize=None,
        tp: Optional[int] = None,
        mesh=None,
        tp_comms=None,
        paged_attention: str = "auto",
        rid_base: int = 0,
        prefix_cache="auto",
        dispatch_retry: Optional[RetryPolicy] = None,
        degraded_cooldown_chunks: int = 8,
        quarantine_policy: str = "requeue",
        fault_injector=None,
        timeline=None,
        registry=None,
        engine_label: Optional[str] = None,
        slo=None,
        program_ledger=None,
        flight_recorder="auto",
        flight_dir: Optional[str] = None,
        profile_dir: Optional[str] = None,
        time_fn: Callable[[], float] = time.monotonic,
        sleep_fn: Callable[[float], None] = time.sleep,
    ):
        if admission not in ("conservative", "eager"):
            raise ValueError(f"unknown admission policy {admission!r}")
        if decode_chunk_size < 1:
            raise ValueError(
                f"decode_chunk_size must be >= 1, got {decode_chunk_size}"
            )
        if quarantine_policy not in ("requeue", "fail"):
            raise ValueError(
                f"unknown quarantine policy {quarantine_policy!r}"
            )
        # quantized serving (ISSUE 13): quantize= is a QuantConfig.
        # Weights: the TARGET model is rebound to its quantization_config'd
        # clone HERE — every jitted program below (prefill buckets, the
        # fused decode/spec chunks, the suffix prefill) traces the
        # dequantize-on-load quantized_matmul forward, one program per
        # bucket exactly like fp32 — and the params setter converts any
        # float tree ONCE per assignment (construction AND later weight
        # swaps). KV: the paged pool stores int8 pages + scale siblings
        # (PagedCacheManager(kv_quant=)); the chunk's gather/scatter
        # transports de/re-quantize in-program. The draft model (if any)
        # stays float: drafts only steer speculation, and the emitted
        # stream never depends on draft numerics. Correctness contract
        # shifts from bit-identity to the pinned logit-divergence budget
        # (tests/serving/test_quantized_engine.py).
        self.quantize = quantize
        self._weight_qcfg = None
        if quantize is not None:
            if quantize.kv is not None and kv_page_size is None:
                raise ValueError(
                    "quantize.kv needs kv_page_size= (quantized KV is "
                    "page-granular — the row layout stays fp32)"
                )
            wq = quantize.weight_qconfig()
            if wq is not None:
                cfg = getattr(model, "config", None)
                if not dataclasses.is_dataclass(cfg) or not any(
                    f.name == "quantization"
                    for f in dataclasses.fields(cfg)
                ):
                    raise ValueError(
                        "quantize.weights needs a model whose config "
                        "carries a 'quantization' field (the llama/mixtral "
                        "families); got "
                        f"{type(cfg).__name__ if cfg is not None else None}"
                    )
                if getattr(cfg, "quantization", None) is not None:
                    raise ValueError(
                        "model already carries a quantization config — "
                        "pass the float model (the engine quantizes) or "
                        "drop quantize="
                    )
                model = model.clone(
                    config=dataclasses.replace(cfg, quantization=wq)
                )
                self._weight_qcfg = wq
        max_seq_len = getattr(getattr(model, "config", None), "max_seq_len", None)
        if max_seq_len is None:
            raise ValueError(
                "ServingEngine needs model.config.max_seq_len (the fixed "
                "slot cache length)"
            )
        # multi-chip serving (ISSUE 14): tp= shards the WHOLE hot path over
        # the TP mesh — params by their nn.Partitioned axis rules (the T5X
        # partitioner pattern: rules own the sharding, the engine's program
        # code never changes), the KV pool/rows on the kv-head axis, slot
        # state and block tables replicated. Every jitted program below then
        # partitions off the placed operands plus the layers' activation
        # constraints: decode_compilations stays 1, the chunk readback stays
        # ONE device_get of replicated scalars/tokens (never sharded KV),
        # and streams are bit-identical to the mesh-free engine at any tp
        # on the CPU mesh proxy. tp_comms= (QuantizedAllReduceConfig)
        # optionally routes the row-parallel all-reduces through the EQuARX
        # int8 ring — a wire-byte dial behind an explicit accuracy opt-in.
        from neuronx_distributed_tpu.parallel.sharding import (
            ServingPartitioner,
            serving_mesh,
        )

        if mesh is not None and tp is None:
            tp = int(mesh.mesh.shape["tp"]) if hasattr(mesh, "mesh") else None
        self.tp = tp
        self._partitioner = None
        if tp is not None:
            state = mesh if mesh is not None else serving_mesh(tp)
            self._partitioner = ServingPartitioner(state)
        self._tp_comms = tp_comms
        if tp_comms is not None and self._partitioner is None:
            raise ValueError(
                "tp_comms= needs a TP mesh (pass tp=/mesh=) — there is no "
                "all-reduce to route on a mesh-free engine"
            )
        # fused paged attention (ISSUE 14, the PR 12 leftover): "fused"
        # streams K/V straight from the physical pool pages through
        # paged_flash_decode_attention's scalar-prefetch block table on
        # TPU; its gather fallback keeps every other backend bit-identical
        # to the "gather" transport. "auto" = fused exactly where the
        # kernel is real (TPU, plain chunk, float pool), gather elsewhere.
        if paged_attention not in ("auto", "gather", "fused"):
            raise ValueError(
                f"unknown paged_attention {paged_attention!r} "
                "(expected 'auto', 'gather' or 'fused')"
            )
        _fusable = (
            kv_page_size is not None
            and draft_model is None
            and (quantize is None or quantize.kv is None)
            and not getattr(
                getattr(model, "config", None), "scan_layers", False
            )
        )
        if paged_attention == "auto":
            paged_attention = (
                "fused"
                if _fusable and jax.devices()[0].platform == "tpu"
                else "gather"
            )
        elif paged_attention == "fused" and not _fusable:
            raise ValueError(
                "paged_attention='fused' needs a paged (kv_page_size=), "
                "non-speculative engine with a float KV pool and "
                "scan_layers=False (the fused transport pairs per-layer "
                "pool leaves with attention calls by layer name)"
            )
        self.paged_attention = paged_attention
        # speculative decoding (ISSUE 9): a draft model turns every decode
        # chunk into `decode_chunk_size` fused draft–verify ROUNDS, each
        # emitting 1..gamma tokens per slot. draft_model=None is a strict
        # no-op: every code path below is byte-for-byte today's
        if draft_model is not None:
            if draft_params is None:
                raise ValueError("draft_model needs draft_params")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            d_cfg = getattr(draft_model, "config", None)
            if getattr(d_cfg, "max_seq_len", None) != max_seq_len:
                raise ValueError(
                    "draft_model.config.max_seq_len "
                    f"({getattr(d_cfg, 'max_seq_len', None)}) must equal the "
                    f"target's ({max_seq_len}) — both caches share the slot "
                    "row length"
                )
            t_vocab = getattr(getattr(model, "config", None), "vocab_size", None)
            if (
                t_vocab is not None
                and getattr(d_cfg, "vocab_size", None) != t_vocab
            ):
                raise ValueError(
                    "draft and target models must share a vocabulary "
                    f"({getattr(d_cfg, 'vocab_size', None)} != {t_vocab})"
                )
        self.model = model
        self.params = params  # property: binds self._params once per assign
        self.draft_model = draft_model
        self.gamma = gamma if draft_model is not None else 1
        # columns one decode dispatch iteration consumes: gamma per
        # speculative round, 1 per plain step — the unit of every cursor
        # wall/admission computation below
        self._round_cols = self.gamma
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.admission = admission
        self.decode_chunk_size = decode_chunk_size
        self.max_queue = max_queue
        self.timeline = timeline
        self._clock = time_fn
        self._sleep = sleep_fn
        self._vocab = getattr(getattr(model, "config", None), "vocab_size", None)
        # dispatch-recovery policy: bounded consecutive failures, waits from
        # the shared decrementing-jitter schedule (utils/retry.py — the same
        # policy class the checkpoint object-store path rides)
        self._dispatch_retry = dispatch_retry or RetryPolicy(
            max_attempts=3, first_wait=0.05, min_wait=0.01
        )
        self._degraded_cooldown = degraded_cooldown_chunks
        self._quarantine_policy = quarantine_policy
        self._faults = fault_injector
        # prefix cache: "auto" (default) builds the standard store; an int
        # sizes it (0 disables); None or a disabled instance restores the
        # full-prefill-only admission path exactly
        if prefix_cache == "auto":
            prefix_cache = PrefixCache()
        elif isinstance(prefix_cache, int):
            prefix_cache = (
                PrefixCache(max_entries=prefix_cache) if prefix_cache > 0
                else None
            )
        if prefix_cache is not None and not prefix_cache.enabled:
            prefix_cache = None
        self.prefix = prefix_cache
        if prefix_cache is not None:
            # paged entries hold ref-counted pool pages instead of copies;
            # whatever drops an entry (LRU churn, poison, clear-on-swap)
            # must release those refs or the pool leaks
            prefix_cache.on_evict = self._on_prefix_evict
        self._prefix_reuses = 0  # reuse-attempt index (poison-hook schedule)
        self._steps_seen = 0  # step() index (flip_bits("params") schedule)
        self._prefill_model, self._decode_model = serving_clones(model)
        # scheduling policy (ISSUE 16): "fifo" (default — bit-identical to
        # the pre-policy engine), "slo" (priority tiers + DWRR token
        # fairness + attainment-feedback admission/preemption), or a
        # SchedulingPolicy instance. The policy owns queue ORDER and
        # victim choice; every mechanism it rides (requeue/resume, slot
        # release) is the existing bit-identical machinery
        self.scheduler = Scheduler(max_tokens_in_flight, policy=scheduling)
        self.policy = self.scheduler.policy
        # paged KV (ISSUE 10): kv_page_size switches the cache path from
        # row-per-slot to block/page granularity — a ref-counted page pool
        # with per-slot device-resident block tables, free-page admission
        # accounting, and ZERO-COPY copy-on-write prefix sharing. None is
        # byte-for-byte the legacy row manager (streams are bit-identical
        # either way; paged buys HBM packing under mixed-length traffic)
        self._page_size = kv_page_size
        if kv_page_size is not None:
            self.cache = PagedCacheManager(
                num_slots, max_seq_len, kv_page_size, kv_num_pages,
                kv_quant=quantize.kv if quantize is not None else None,
            )
            self.cache.reclaim = self._reclaim_prefix_entry
        else:
            if kv_num_pages is not None:
                raise ValueError("kv_num_pages needs kv_page_size")
            if kv_host_pages is not None:
                raise ValueError("kv_host_pages needs kv_page_size")
            self.cache = SlotCacheManager(num_slots)
        # tiered KV (ISSUE 19): a bounded host-RAM store behind the page
        # pool — the reclaim valve spills cold prefix entries' pages there
        # instead of dropping them, and the admission pre-pass prefetches
        # matched pages back while the hitting request still queues. None
        # (the default) keeps the single-tier engine byte-identical
        self.tier = (
            HostPageStore(kv_host_pages)
            if kv_host_pages is not None else None
        )
        self._spill_index = 0     # spill-attempt index (chaos schedules)
        self._prefetch_index = 0  # prefetch-attempt index (chaos schedules)
        if self._partitioner is not None:
            # KV storage commits to the mesh at allocation (kv-head axis
            # over tp where it divides); every donated successor keeps it
            self.cache.placement = self._partitioner.place_kv
        # draft-side twins: mode clones, a SECOND donated cache collection
        # (admit/free/recover/quarantine mirrored 1:1 with the target's),
        # and per-bucket draft prefill programs. The draft cache cursor
        # tracks the target's in lockstep — both consume gamma columns per
        # executed round
        if draft_model is not None:
            self.draft_params = draft_params  # property: binds once
            self._draft_prefill_model, self._draft_decode_model = (
                serving_clones(draft_model)
            )
            # the draft twin rides the same manager class (and, when paged,
            # its own pool of the same geometry — lifecycles mirror 1:1)
            self.draft_cache = (
                PagedCacheManager(
                    num_slots, max_seq_len, kv_page_size, kv_num_pages
                )
                if kv_page_size is not None
                else SlotCacheManager(num_slots)
            )
            if self._partitioner is not None:
                self.draft_cache.placement = self._partitioner.place_kv
        else:
            self._draft_params_src = None
            self._draft_params = None
            self.draft_cache = None
        self._draft_prefill_fns: Dict[int, Callable] = {}
        # tenant/SLO attribution (ISSUE 11): slo= is an SLOSpec (one
        # contract for every tenant) or a {tenant: SLOSpec} dict;
        # engine_label= makes every metric a child of an engine-labeled
        # family so N engines can share one registry/scrape endpoint
        self.metrics = ServingMetrics(
            num_slots, registry=registry, engine_label=engine_label, slo=slo
        )
        # late policy wiring: the SLO policy reads metrics/prefix/cache
        # feedback surfaces (all host state; FIFO ignores the engine)
        self.policy.bind(self)
        # observability layer (ISSUE 8): request-scoped flow tracing on the
        # shared timeline, and an always-on flight recorder whose ring is
        # dumped as a redacted post-mortem the moment the engine HALTs.
        # Every emit below takes host scalars the loop already owns — the
        # pinned host-sync budgets (tests/serving/test_host_sync.py) hold
        # with all of this enabled
        self.tracer = RequestTracer(timeline)
        if flight_recorder == "auto":
            flight_recorder = FlightRecorder(
                dump_dir=flight_dir, subsystem="serving"
            )
        self.flight = flight_recorder  # None disables
        # device-efficiency observability (ISSUE 12): every jitted program
        # below registers in a ProgramLedger — dispatch counts, compile
        # wall, compiler-reported FLOPs/bytes (lazy cost analysis at
        # export, never on the hot path) and roofline telemetry off the
        # chunk walls the loop already measures. Pass program_ledger= to
        # share one ledger (e.g. bench's memory_analysis=True instance);
        # the default rides the engine's labeled metrics view
        self.programs = (
            program_ledger if program_ledger is not None
            else ProgramLedger(
                view=self.metrics.view, prefix="serving",
                subsystem="serving", timeline=timeline,
            )
        )
        self.cache.register_programs(self.programs)
        if self.draft_cache is not None:
            self.draft_cache.register_programs(self.programs, prefix="draft_")
        self._profile_dir = profile_dir
        self._profiling = False
        # host-side slot bookkeeping (scheduling only — the decode-visible
        # per-slot state lives on device in self._state)
        self._active = np.zeros((num_slots,), bool)
        self._slot_req: List[Optional[Request]] = [None] * num_slots
        self._on_token: Dict[int, Callable[[Request, int], None]] = {}
        # rid_base namespaces request ids across engines (the replica
        # router re-homes live Request objects between replicas — two
        # engines must never mint the same rid)
        self._next_rid = int(rid_base)
        self._prefill_fns: Dict[int, Callable] = {}
        self._state = self._fresh_slot_state()
        # disaggregated serving (ISSUE 14): True routes ALL prefill work to
        # external workers — step() keeps decoding but never self-admits;
        # the DisaggregatedServer pulls from the queue, prefills on its
        # workers, and hands contexts back through admit_staged()
        self.external_prefill = False
        # fault-tolerance state machine
        self._halted = False
        self._halt_reason: Optional[str] = None
        self._draining = False
        self._consecutive_dispatch_failures = 0
        self._had_dispatch_failure = False
        self._chunks_since_failure = 0
        self._dispatch_attempts = 0  # includes failed attempts (hook index)
        self._readbacks = 0  # successful readbacks (poison-hook index)
        self._prefill_calls = 0  # prefill attempts (prefill-hook index)
        self._consecutive_prefill_failures = 0
        self._last_health = EngineHealth.OK
        # the fused decode chunk: cache AND slot state donated — XLA updates
        # both in place instead of materializing a fresh cache pytree. With
        # a draft model the SPECULATIVE chunk (both caches + state donated)
        # is the hot program; the plain chunk is then built LAZILY, only if
        # a failed speculative dispatch ever needs the non-speculative
        # fallback
        if draft_model is not None:
            self._spec_chunk = jax.jit(
                speculative_decode_chunk(
                    self._decode_model, self._draft_decode_model,
                    decode_chunk_size, gamma, max_seq_len,
                    page_size=kv_page_size,
                ),
                donate_argnums=(2, 3, 4),
            )
            # ledger proxies rebind AFTER the jax.jit assignment so
            # graftlint's donation index (GL01) keeps seeing the literal
            # donate_argnums on the binding; the proxy forwards
            # _cache_size()/lower, so the compile-count properties below
            # read through unchanged
            self._spec_chunk = self.programs.wrap(
                "spec_decode_chunk", self._comms_scoped(self._spec_chunk)
            )
            self._decode_chunk = None
        else:
            self._spec_chunk = None
            self._decode_chunk = jax.jit(
                chunked_decode_step(
                    self._decode_model, decode_chunk_size, max_seq_len,
                    page_size=kv_page_size,
                    paged_attention=(
                        self.paged_attention
                        if kv_page_size is not None else "gather"
                    ),
                ),
                donate_argnums=(1, 2),
            )
            self._decode_chunk = self.programs.wrap(
                "decode_chunk", self._comms_scoped(self._decode_chunk)
            )
        # per_instance: module-level helpers share a pjit cache across
        # engines in this jax (PR 4's lambda-wrapper note) — a fresh
        # function object per engine keeps compile counts, and the
        # ledger's compile/signature detection, per-engine truthful
        self._slot_write = jax.jit(per_instance(_slot_write), donate_argnums=(0,))
        self._slot_write = self.programs.wrap("slot_write", self._slot_write)
        self._slot_clear = jax.jit(per_instance(_slot_clear), donate_argnums=(0,))
        self._slot_clear = self.programs.wrap("slot_clear", self._slot_clear)
        self._first_token = self.programs.wrap(
            "first_token", jax.jit(per_instance(sample_row))
        )
        # prefix-reuse programs (compiled lazily, only when the cache hits):
        # suffix prefill keys on the chunk bucket, extract/seed on the
        # storage bucket, the fingerprint on the entry shapes. NOTHING here
        # donates — a stored entry must stay a live COPY (the decode chunk's
        # donation regime must never be able to consume prefix storage)
        self._suffix_fn = self.programs.wrap(
            "suffix_prefill",
            self._comms_scoped(jax.jit(suffix_prefill_step(self._decode_model))),
        )
        # per-engine lambda wrappers: in this jax (0.4.37), _cache_size()
        # is SHARED between jax.jit wrappers of the same function object
        # (two jax.jit(f) both read 1 after either is called — verified),
        # so jitting the module-level helpers directly would cross-pollute
        # the compile counts across engines
        self._extract_fn = self.programs.wrap("prefix_extract", jax.jit(
            lambda cache, start, m, bucket: extract_cache_prefix(
                cache, start, m, bucket
            ),
            static_argnums=(3,),
        ))
        self._seed_fn = self.programs.wrap("prefix_seed", jax.jit(
            lambda prefix, m, start, length: seed_cache_prefix(
                prefix, m, start, length
            ),
            static_argnums=(3,),
        ))
        self._fingerprint_fn = self.programs.wrap(
            "prefix_fingerprint", jax.jit(lambda tree: cache_fingerprint(tree))
        )
        # integrity sentinel (ISSUE 20): bit-level fingerprint programs,
        # built LAZILY — an engine that is never probed (and a pool whose
        # paged entries never reach fingerprint validation) compiles
        # nothing extra, so existing workloads' compile ledgers and
        # host-sync budgets are untouched
        self._integrity_fp_fn = None
        self._pages_fp_fn = None
        # HBM ledger (ISSUE 12): the engine's static residents registered
        # as weakref closures over live trees — bytes are leaf.nbytes
        # metadata (readable even mid-donation), reconciled against
        # Device.memory_stats() limits at snapshot time. plan() sizes
        # budgets in KV pages (paged) / slot rows (row mode)
        self.hbm = HBMLedger(view=self.metrics.view)

        def _res(fn):
            return weak_reader(self, fn)

        self.hbm.add_resident("params", _res(lambda e: tree_nbytes(e._params)))
        self.hbm.add_resident(
            "slot_state", _res(lambda e: tree_nbytes(e._state))
        )
        if kv_page_size is not None:
            self.hbm.add_resident(
                "kv_pages", _res(lambda e: e.cache.nbytes),
                unit_bytes=_res(lambda e: e.cache.page_nbytes),
                count=_res(lambda e: e.cache.alloc.capacity), unit="page",
            )
            if self.tier is not None:
                # host-tier resident (ISSUE 19): spilled pages' host
                # bytes, sized in pages against plan(host_budget_bytes=)
                # — never against device headroom
                self.hbm.add_resident(
                    "kv_host_pages", _res(lambda e: e.tier.nbytes),
                    unit_bytes=_res(lambda e: e.cache.page_nbytes),
                    count=_res(lambda e: e.tier.used_pages), unit="page",
                    tier="host",
                )
        else:
            self.hbm.add_resident(
                "kv_cache", _res(lambda e: e.cache.nbytes),
                unit_bytes=_res(lambda e: e.cache.slot_nbytes),
                count=num_slots, unit="slot",
            )
        if draft_model is not None:
            self.hbm.add_resident(
                "draft_params", _res(lambda e: tree_nbytes(e._draft_params))
            )
            self.hbm.add_resident(
                "draft_kv", _res(lambda e: e.draft_cache.nbytes)
            )
        if prefix_cache is not None:
            self.hbm.add_resident(
                "prefix_cache",
                _res(lambda e: e.prefix.nbytes if e.prefix is not None else 0),
            )
        # snapshot()["programs"] / ["hbm"] ride the metrics export (weakly
        # — a kept metrics object never pins a retired engine's ledgers)
        self.metrics.attach_device_efficiency(self.programs, self.hbm)
        # compile-event gauges: evaluated lazily at registry export (a
        # _cache_size read is host metadata), zero cost per step. WEAK
        # self-reference: a registry an operator keeps for a final scrape
        # must not pin a retired engine (model, params, KV cache)
        ref = weakref.ref(self)

        def _export(attr):
            def fn():
                engine = ref()
                return getattr(engine, attr) if engine is not None else -1
            return fn

        # own_gauge honors engine_label: labeled engines export these as
        # engine-labeled family children, so shared registries never
        # last-writer-wins another engine's export gauges
        gauge = self.metrics.own_gauge
        gauge(
            "serving_decode_compilations",
            help="distinct decode programs XLA compiled (invariant: 1)",
        ).set_fn(_export("decode_compilations"))
        gauge(
            "serving_prefill_compilations",
            help="distinct full+suffix prefill programs compiled",
        ).set_fn(_export("prefill_compilations"))
        gauge(
            "serving_queue_depth", help="queued (unfinished) requests"
        ).set_fn(_export("queue_depth"))
        if kv_page_size is not None:
            def _page_export(fn):
                def read():
                    engine = ref()
                    return fn(engine.cache) if engine is not None else -1
                return read

            gauge(
                "serving_kv_pages_total",
                help="usable KV pool pages (reserved + quarantined excluded)",
            ).set_fn(_page_export(lambda c: c.alloc.capacity))
            gauge(
                "serving_kv_pages_free", help="KV pool pages on the free list"
            ).set_fn(_page_export(lambda c: c.alloc.free_pages))
            gauge(
                "serving_kv_pages_mapped",
                help="KV pool pages mapped by some slot's block table",
            ).set_fn(_page_export(lambda c: c.pages_mapped))
            if self.tier is not None:
                def _tier_export(fn):
                    def read():
                        engine = ref()
                        return (
                            fn(engine.tier) if engine is not None else -1
                        )
                    return read

                gauge(
                    "serving_kv_host_pages_used",
                    help="spilled KV pages resident in the host tier",
                ).set_fn(_tier_export(lambda t: t.used_pages))
                gauge(
                    "serving_kv_host_bytes",
                    help="host-RAM bytes held by spilled KV pages",
                ).set_fn(_tier_export(lambda t: t.nbytes))

    def _fresh_slot_state(self):
        b = self.num_slots
        state = {
            "tok": jnp.zeros((b,), jnp.int32),
            "keys": jnp.zeros((b, 2), jnp.uint32),
            "active": jnp.zeros((b,), jnp.bool_),
            "temp": jnp.ones((b,), jnp.float32),
            "topk": jnp.zeros((b,), jnp.int32),
            "topp": jnp.ones((b,), jnp.float32),
            "remaining": jnp.zeros((b,), jnp.int32),
            "eos": jnp.full((b,), -1, jnp.int32),
        }
        if self._partitioner is not None:
            # committed-replicated over the mesh so every donated
            # successor keeps the layout (and no uncommitted-operand
            # recompile can ever hide here — the PR 5 zeros lesson)
            state = self._partitioner.replicate(state)
        return state

    # --- paged-KV helpers ---------------------------------------------------

    def _comms_scoped(self, fn):
        """Wrap a model-forward jit so its trace runs under the engine's
        ``tp_comms`` scope (no-op without one)."""
        if self._tp_comms is None:
            return fn
        from neuronx_distributed_tpu.parallel.quantized_collectives import (
            tp_comms,
        )

        return _TraceScope(fn, lambda: tp_comms(self._tp_comms))

    def _on_prefix_evict(self, entry) -> None:
        """PrefixCache eviction hook: a PAGED entry leaving the store (LRU
        churn, poison, clear-on-swap) releases its pool page refs — pages
        still mapped by a decoding slot's block table survive through that
        slot's own refs (CoW), pages held only by the entry free now.
        Tiered: a host-resident entry drops its host pages too, and a
        prefetched-but-unconsumed entry's device pages count as wasted
        prefetch work before their holds are voided."""
        if entry.page_ids:
            if self._page_size is not None and (
                self.cache.prefetch_held(entry.page_ids)
            ):
                self.metrics.record_prefetch_wasted(len(entry.page_ids))
                self.cache.release_prefetched(entry.page_ids)
            self.cache.unpin_pages(entry.page_ids)
            entry.page_ids = None
        if entry.host_ids and self.tier is not None:
            self.tier.drop(entry.host_ids)
            entry.host_ids = None

    def _reclaim_prefix_entry(self) -> bool:
        """Page-pressure valve (installed as ``cache.reclaim``): evict the
        least-recently-used UNPINNED prefix entry so its pages can serve a
        new admission. Never frees a still-mapped page — eviction only
        drops the entry's refs. With a host tier the entry's pages are
        SPILLED there first (one batched device->host pull) and the entry
        stays in the trie host-resident; a full/failed spill degrades to
        the plain eviction above. Entries whose pages a queued request's
        prefetch already claimed are skipped — reclaiming them would
        un-do work the admission fit math has already counted."""
        if self.prefix is None:
            return False
        for e in self.prefix.entries:  # LRU first
            if e.refs == 0 and e.page_ids:
                if self.cache.prefetch_held(e.page_ids):
                    continue
                if self._spill_entry(e):
                    return True
                self.prefix.evict_entry(e)
                self.metrics.record_prefix_eviction()
                return True
        return False

    def _spill_entry(self, entry) -> bool:
        """Move one cold prefix entry's pages device->host. True = the
        pages are free-able (the entry is now host-resident); False = no
        tier / no room / spill fault — the caller falls back to plain
        eviction. A failed spill NEVER leaks: nothing is unpinned until
        the host copy is stored."""
        if self.tier is None or not entry.page_ids:
            return False
        n = len(entry.page_ids)
        if n > self.tier.free_pages:
            return False
        attempt = self._spill_index
        self._spill_index += 1
        try:
            if self._faults is not None:
                self._faults.on_spill(attempt)
            items, nbytes = self.cache.spill_pages(entry.page_ids)
            host_ids = self.tier.put(entry.page_ids, items)
        except Exception:
            self.metrics.record_spill_failure()
            return False
        self.cache.unpin_pages(entry.page_ids)
        entry.page_ids = None
        entry.host_ids = host_ids
        entry.hit_tier = "host"
        self.metrics.record_spill(n, nbytes)
        if self.timeline is not None:
            self.timeline.instant(
                "kv_spill", "serving",
                args={"pages": n, "bytes": nbytes},
            )
        return True

    def _prefetch_entry(self, entry, late: bool = False) -> bool:
        """Bring one host-resident prefix entry's pages back device-side.
        The device write is the pool's existing import program — an async
        host->device dispatch (zero syncs) that overlaps the in-flight
        decode chunk. Returns True when the entry is device-resident
        after the call. Fingerprint mismatch or an injected prefetch
        fault evicts the entry (the admission falls back to a full
        prefill — bit-identical by construction); pool exhaustion leaves
        the entry host-resident to retry later."""
        if self.tier is None or not entry.host_ids:
            return entry.page_ids is not None
        host_ids = entry.host_ids
        n = len(host_ids)
        attempt = self._prefetch_index
        self._prefetch_index += 1
        try:
            if self._faults is not None:
                self._faults.on_prefetch(
                    attempt, store=self.tier, host_ids=host_ids
                )
            if not self.tier.verify(host_ids):
                # corrupted host copy: reject the WHOLE fetch, drop the
                # entry — the next admission re-prefills from tokens
                self.metrics.record_host_page_poisoned()
                self.metrics.record_prefix_validation_failure()
                if self.timeline is not None:
                    self.timeline.instant(
                        "kv_host_poisoned", "serving", args={"pages": n}
                    )
                self.prefix.evict_entry(entry)
                self.metrics.record_prefix_eviction()
                return False
            items, nbytes = self.tier.get(host_ids)
            ids = self.cache.prefetch_pages(items, n)
        except PageExhausted:
            return False  # stay host-resident; retry on a later pass
        except Exception:
            self.metrics.record_prefetch_failure()
            self.prefix.evict_entry(entry)
            self.metrics.record_prefix_eviction()
            return False
        self.cache.hold_prefetched(ids)
        entry.page_ids = tuple(int(i) for i in ids)
        entry.host_ids = None
        entry.hit_tier = "host"
        self.tier.drop(host_ids)
        self.metrics.record_prefetch(n, nbytes, late=late)
        if self.timeline is not None:
            self.timeline.instant(
                "kv_prefetch", "serving",
                args={"pages": n, "bytes": nbytes, "late": late},
            )
        return True

    def _prefetch_for_queue(self) -> None:
        """Admission pre-pass (ISSUE 19): peek the front of the queue and
        start host->device prefetches for any matched SPILLED prefix
        entries before the requests are admitted — the transfer rides the
        pool's async import dispatch and overlaps the current chunk's
        device time, so the hit is device-resident by rebind time.
        Policy-blind and LRU-neutral (``peek`` does not refresh recency);
        a prefetch for a request admitted later is merely early."""
        if self.tier is None or self.prefix is None:
            return
        window = max(self.cache.free_slots, 1)
        for req in self.scheduler.upcoming(window):
            hit = self.prefix.peek(req.context_ids)
            if hit is None:
                continue
            entry, m_use = hit
            if entry.host_ids and m_use > 0:
                self._prefetch_entry(entry)

    def _paged_layout(self, p: int, rem_cols: int, proj: int):
        """(padded, cursor target) for a paged admission at projected
        cursor ``proj``: the padded bucket as ever, with the target bumped
        (< page_size gap columns) so the context START lands on a page
        boundary — the alignment that makes whole context pages shareable.
        When the bump would push the request past the row end that the
        exact-length bucket avoids, fall back to ``padded = p`` (the same
        keep-every-feasible-request-admittable trade ``_bucket`` makes)."""
        padded = _bucket(p, self.max_seq_len, rem_cols)
        target = self.cache.aligned_target(max(proj, padded), p)
        if padded > p and target + rem_cols > self.max_seq_len:
            padded = p
            target = self.cache.aligned_target(max(proj, p), p)
        return padded, target

    def _chunk_width_cols(self, active) -> int:
        """Columns the next chunk can actually WRITE: the fused chunk
        freezes a slot when its budget runs out, so no more than the
        largest remaining generation among active slots ever executes
        (steps on the plain path, rounds — gamma columns each — on the
        speculative path). Clamping the page demand to this keeps the
        per-chunk window consistent with the admission/door accounting,
        which sizes requests by their REMAINING tokens — an unclamped full
        chunk window could demand pages the door check never charged and
        livelock a tightly-sized pool at the page-pressure wall."""
        max_rem = max(
            (
                self._slot_req[int(s)].remaining_new_tokens
                for s in active
                if self._slot_req[int(s)] is not None
            ),
            default=self.decode_chunk_size,
        )
        return min(self.decode_chunk_size, max(max_rem, 1)) * self._round_cols

    def _ensure_decode_pages(self) -> bool:
        """Map pool pages under every active slot's next write window (both
        caches on a speculative engine). False = the page-pressure wall:
        the caller preempts-and-rewinds, exactly like the cursor wall."""
        active = np.flatnonzero(self._active)
        width = self._chunk_width_cols(active)
        if not self.cache.ensure_decode_window(active, width):
            return False
        if self.draft_cache is not None and not (
            self.draft_cache.ensure_decode_window(active, width)
        ):
            return False
        return True

    def _apply_page_poison(self, readback: int) -> set:
        """Consult the injector's page-poison schedule (paged engines):
        each scheduled page is retired from the pool and every ACTIVE
        request whose block table maps it is requeued from the last chunk
        boundary (its chunk output discarded, tokens/keys host-current —
        bit-identical resume in a different slot/pages). The slot indices
        return to rotation; only the PAGE is lost. Prefix entries pinning a
        poisoned page are evicted (their shared content is suspect).
        Returns the victim slot set (the caller skips their readback)."""
        victims: set = set()
        if self._faults is None or self._page_size is None:
            return victims
        pages = self._faults.on_page_readback(
            readback, lambda s: self.cache.slot_pages(int(s)), self._active
        )
        if not pages:
            return victims
        now = self._now()
        for page in pages:
            slots = self.cache.quarantine_page(int(page))
            self.metrics.record_page_quarantine(int(page), len(slots))
            if self.timeline is not None:
                self.timeline.instant(
                    f"quarantine page {int(page)}", "serving",
                    args={"slots": [int(s) for s in slots]},
                )
            if self.flight is not None:
                self.flight.record("page_quarantine", page=int(page),
                                   slots=[int(s) for s in slots])
            if self.prefix is not None:
                for e in list(self.prefix.entries):
                    if e.page_ids and int(page) in e.page_ids:
                        self.prefix.evict_entry(e)
                        self.metrics.record_prefix_eviction()
            victims.update(int(s) for s in slots if self._active[int(s)])
        requeue = []
        for slot in sorted(victims):
            req = self._slot_req[slot]
            self._slot_req[slot] = None
            self._active[slot] = False
            self._state = self._slot_clear(self._state, np.int32(slot))
            self.cache.free(slot)
            if self.draft_cache is not None:
                self.draft_cache.free(slot)
            if req is None:
                continue
            req.slot = None
            if self._quarantine_policy == "requeue" and not req.finished:
                self.tracer.step(req.rid, "page_quarantine_requeue",
                                 args={"slot": slot})
                requeue.append(req)
            else:
                req.state = RequestState.FAILED
                req.error = f"slot {slot} mapped a poisoned KV page"
                req.finish_time = now
                self.metrics.record_failed(req, now, kind="quarantine")
                self.tracer.end(req.rid, "failed",
                                args={"kind": "page_quarantine", "slot": slot})
                self._on_token.pop(req.rid, None)
        if requeue:
            self.scheduler.requeue_front(requeue)
        if self.cache.alloc.capacity == 0:
            self._halt("all KV pages quarantined")
        return victims

    # --- public API ---------------------------------------------------------

    @property
    def params(self):
        """The engine's weights. Assignment rebinds the params pytree the
        jitted prefill/decode programs receive — ONCE per assignment, not
        per step (the hot path never rebuilds it), so weight swaps still
        take effect on the next dispatch."""
        return self._params_src

    @params.setter
    def params(self, value):
        qcfg = getattr(self, "_weight_qcfg", None)
        if qcfg is not None:
            from neuronx_distributed_tpu.quantization.utils import (
                is_quantized_tree,
                quantize_param_tree,
            )

            # a float tree converts ONCE per assignment (construction and
            # hot weight swaps alike); a pre-quantized tree — an offline
            # quantize_param_tree output, a loaded quantized checkpoint —
            # binds as-is. Either way the bound tree matches the quantized
            # model clone's declaration structure exactly
            if not is_quantized_tree(value):
                value = quantize_param_tree(value, qcfg)
        self._params_src = value
        if getattr(self, "_partitioner", None) is not None:
            # TP placement happens HERE, once per assignment — the axis
            # rules (nn.Partitioned metadata) own the layout, every jitted
            # program below just follows the committed operands
            value = self._partitioner.shard_params(value)
        self._params = dict(value)
        # a weight swap invalidates every stored prefix: its KV was computed
        # under the OLD weights, and the cache-off path would recompute it —
        # serving it would silently break bit-identity (and correctness)
        prefix = getattr(self, "prefix", None)  # None during __init__
        if prefix is not None:
            dropped = prefix.clear()
            metrics = getattr(self, "metrics", None)
            if dropped and metrics is not None:
                metrics.record_prefix_eviction(dropped)

    @property
    def draft_params(self):
        """The draft model's weights (speculative serving only). Assignment
        rebinds the pytree the speculative chunk receives once, like
        ``params``. A draft swap mid-flight is SAFE for correctness — the
        emitted stream never depends on draft quality — but slots admitted
        under the old draft keep old-draft KV until they retire, so
        acceptance may dip until the fleet turns over."""
        return self._draft_params_src

    @draft_params.setter
    def draft_params(self, value):
        if value is None:
            raise ValueError("draft_params cannot be unset on a live engine")
        self._draft_params_src = value
        if getattr(self, "_partitioner", None) is not None:
            value = self._partitioner.shard_params(value)
        self._draft_params = dict(value)

    def _now(self) -> float:
        """The engine's scheduling clock — the injected ``time_fn``,
        optionally skewed by the fault injector (chaos tests drive deadline
        paths without sleeping)."""
        now = self._clock()
        if self._faults is not None:
            now = self._faults.now(now)
        return now

    def submit(
        self,
        prompt_ids,
        config: GenerationConfig = GenerationConfig(),
        key=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
        deadline_s: Optional[float] = None,
        queue_timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> Request:
        """Enqueue one request; returns its live ``Request`` (``tokens``
        fills in as the engine steps). ``key`` defaults to a per-request
        PRNGKey; pass the key you would give ``generate`` to reproduce its
        stream exactly.

        ``deadline_s`` bounds the request end to end (sheds to ``TIMED_OUT``
        at the next chunk boundary once exceeded, keeping any tokens already
        streamed); ``queue_timeout_s`` sheds it if it has not been admitted
        in time — both relative to submission on the engine clock. The
        queue timeout governs FIRST admission only: once admitted, a
        request requeued by preemption or dispatch recovery answers only to
        ``deadline_s``.

        ``tenant``/``priority`` (ISSUE 11) attribute the request for
        observability: per-tenant latency histograms, shed/timeout/reject
        attribution, SLO attainment (``slo=`` specs), trace-flow and
        flight-recorder tagging. Host strings only — attribution adds no
        device work and no host syncs. Scheduling is unaffected in this
        PR; the SLO-aware scheduler consumes these fields.

        Raises :class:`RejectedError` when the engine is draining/halted or
        the bounded queue (``max_queue``) is full, and ``ValueError`` for
        requests that could NEVER be placed (so an impossible request fails
        at the door instead of livelocking ``run()`` at the queue head)."""
        tenant = str(tenant) if tenant is not None else "default"
        priority = str(priority) if priority is not None else "standard"
        health = self.health()
        if health in (EngineHealth.DRAINING, EngineHealth.HALTED):
            depth = self.scheduler.queued
            self.metrics.record_reject(
                depth, health.value, tenant=tenant, now=self._now()
            )
            raise RejectedError(
                f"engine is {health.value}; not accepting new requests",
                queue_depth=depth,
            )
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if config.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if queue_timeout_s is not None and queue_timeout_s <= 0:
            raise ValueError(
                f"queue_timeout_s must be > 0, got {queue_timeout_s}"
            )
        # permanently-unplaceable guards: queueing a request no admission
        # round can ever select would livelock run() behind a FIFO head.
        # The seq-len class (prompt + generation over max_seq_len) is the
        # shared generate() contract below — it also subsumes the prefill
        # bucket, because _bucket falls back to the exact prompt length
        # whenever padding would not leave room for the generation; the
        # token-budget class needs its own check against the scheduler
        validate_generate_args(
            self.model, prompt[None], config.max_new_tokens, None
        )
        if self.draft_model is not None and (
            prompt.size + config.max_new_tokens + self.gamma - 1
            > self.max_seq_len
        ):
            # the speculative twin of the solo guard: the LAST round's
            # gamma-token verify window must fit the row even when the
            # context has grown to prompt + max_new - 1 (otherwise the
            # final token could never be emitted — rewind/preempt would
            # livelock re-admitting a context whose window never fits)
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({config.max_new_tokens}) + gamma-1 ({self.gamma - 1}) "
                f"exceeds max_seq_len ({self.max_seq_len}) — speculative "
                "serving needs window headroom for the final round"
            )
        budget = self.scheduler.max_tokens_in_flight
        if budget is not None and prompt.size + config.max_new_tokens > budget:
            raise ValueError(
                f"request footprint ({prompt.size + config.max_new_tokens}) "
                f"exceeds max_tokens_in_flight ({budget}); it could never "
                "be admitted"
            )
        if self._page_size is not None:
            # free-page twin of the seq-len-class guard, kept EXACT: the
            # request's worst-case page footprint ALONE (empty engine,
            # cursor rewound) must fit the pool or no admission round can
            # ever select it — fail at the door, not livelocked at the head
            rem_cols = config.max_new_tokens + self._round_cols - 1
            _, t0 = self._paged_layout(prompt.size, rem_cols, 0)
            span0 = self.cache.page_span(
                t0 - prompt.size, min(self.max_seq_len, t0 + rem_cols)
            )
            if span0 > self.cache.alloc.capacity:
                raise ValueError(
                    f"request needs {span0} KV pages even alone; the pool "
                    f"holds {self.cache.alloc.capacity} usable pages — it "
                    "could never be placed"
                )
        # backpressure: a bounded queue rejects loudly instead of absorbing
        # an unserviceable backlog
        depth = self.scheduler.queued
        if self.max_queue is not None and depth >= self.max_queue:
            self.metrics.record_reject(
                depth, "queue full", tenant=tenant, now=self._now()
            )
            if self.timeline is not None:
                self.timeline.instant(
                    "reject", "serving",
                    args={"queue_depth": depth, "tenant": tenant},
                )
            raise RejectedError(
                f"queue full ({depth} >= max_queue {self.max_queue})",
                queue_depth=depth,
            )
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = jax.random.PRNGKey(rid)
        req = Request(
            rid=rid, prompt=prompt, config=config, key=_key_data(key),
            tenant=tenant, priority=priority,
        )
        req.submit_time = self._now()
        if deadline_s is not None:
            req.deadline = req.submit_time + deadline_s
        if queue_timeout_s is not None:
            req.queue_deadline = req.submit_time + queue_timeout_s
        if on_token is not None:
            self._on_token[rid] = on_token
        self.scheduler.submit(req)
        self.metrics.record_submit(req, req.submit_time)
        if self.timeline is not None:
            self.timeline.instant(f"submit r{rid}", "serving")
        # open the request's trace flow: every later lifecycle event links
        # back to this id, so one Perfetto flow is the request's whole life
        # (tenant/priority on the opening event tag the whole flow)
        self.tracer.begin(
            rid,
            args={
                "prompt_len": int(prompt.size),
                "tenant": tenant,
                "priority": priority,
            },
        )
        return req

    def cancel(self, rid: int) -> bool:
        """Cancel a request. Queued: dropped immediately; running: its slot
        is reaped at the next chunk boundary."""
        req = self.scheduler.get(rid)
        if req is None or req.finished:
            return False
        was_queued = req.slot is None
        ok = self.scheduler.cancel(rid)
        if ok and was_queued:
            self.metrics.record_cancel(req, self._now())
            self.tracer.end(rid, "cancelled", args={"where": "queued"})
            # queued requests never reach _release_slot — drop the callback
            # here or it leaks for the engine's lifetime
            self._on_token.pop(rid, None)
        return ok

    # --- router / disaggregation surface (ISSUE 14) -------------------------

    def page_pressure(self) -> float:
        """Projected page demand of all admitted + queued work relative to
        pool capacity (0.0 on row engines). This is the router's
        overcommit signal: queue depth alone says nothing about how much
        POOL a replica's backlog will claim, so shared-prefix affinity
        steering a long-context burst at one replica would overcommit its
        pages while its queue still looked short. Worst-case accounting
        (per-request aligned spans, sharing ignored) — a value >= 1.0
        means the backlog cannot coexist and the replica will be churning
        the preemption wall.

        Tiered (ISSUE 19): pages held by cold prefix entries that CAN
        spill to host room are reclaimable-without-loss, so they relieve
        pressure — capacity grows by min(host free, reclaimable). The
        untiered math is byte-identical to the pre-tier engine."""
        if self._page_size is None:
            return 0.0
        cap = self.cache.alloc.capacity
        if self.tier is not None:
            cap += min(self.tier.free_pages, self.cache.reclaimable_pages())
        cap = max(cap, 1)
        span = 0
        live = [r for r in self._slot_req if r is not None]
        live += [r for r in self.scheduler.queued_requests]
        for r in live:
            cols = (
                len(r.prompt) + len(r.tokens) + r.remaining_new_tokens
                + self._round_cols - 1
            )
            # +1: the page-alignment gap a paged admission may pay
            span += self.cache.page_span(0, min(cols, self.max_seq_len)) + 1
        return span / cap

    def load_score(self, tenant: Optional[str] = None) -> float:
        """The router's balancing signal: work in the building (active
        slots + queued requests) plus the page-pressure term scaled to
        slot units, so a replica whose pool is nearly committed reads as
        loaded even with a short queue.

        With ``tenant=`` the score grows the policy's per-tenant
        attainment term (ISSUE 16 tentpole (d)): a replica where THIS
        tenant's SLO is under water reads as more loaded for its next
        request, so the router steers toward the replica where the
        tenant's SLO is healthiest. The FIFO policy's bias is always 0.0
        — tenant-blind routing is unchanged."""
        score = (
            float(int(self._active.sum()) + self.scheduler.queued)
            + self.page_pressure() * self.num_slots
        )
        if tenant is not None:
            score += self.policy.route_bias(tenant) * self.num_slots
        return score

    def adopt(self, req: Request, on_token=None) -> Request:
        """Take over a live ``Request`` minted by ANOTHER engine (the
        replica router's re-homing path — a HALTED replica's requeued work
        moves to survivors). The request keeps its rid (engines under one
        router mint from disjoint ``rid_base`` ranges), its streamed
        tokens, and its host-current key, so the continuation here is
        bit-identical to the stream the dead replica would have produced:
        admission re-prefills ``context_ids`` and resumes at ``req.key``
        — the same contract as preemption-resume."""
        health = self.health()
        if health in (EngineHealth.DRAINING, EngineHealth.HALTED):
            raise RejectedError(
                f"engine is {health.value}; cannot adopt re-homed work",
                queue_depth=self.scheduler.queued,
            )
        if req.rid in self.scheduler.requests:
            raise ValueError(
                f"rid {req.rid} already known to this engine — replicas "
                "under one router must mint from disjoint rid_base ranges"
            )
        req.slot = None
        self.scheduler.submit(req)
        self.metrics.record_adopt(req, self._now())
        if on_token is not None:
            self._on_token[req.rid] = on_token
        self.tracer.begin(
            req.rid,
            args={
                "prompt_len": int(len(req.prompt)),
                "tenant": req.tenant,
                "priority": req.priority,
                "rehomed": True,
                "tokens_streamed": len(req.tokens),
            },
        )
        return req

    def release_queued(self, rid: int):
        """Withdraw a never-admitted QUEUED request from this engine so
        another replica can :meth:`adopt` it (the router's live-join
        rebalancing and drain paths). Returns ``(request, on_token)`` —
        the live request plus its streaming callback, both now owned by
        the caller — or ``(None, None)`` when the rid is not releasable
        (unknown, finished, already admitted, or preempted-after-
        admission: in-flight work belongs to this engine until it halts
        or finishes)."""
        req = self.scheduler.get(rid)
        if req is None or req.admit_time is not None:
            return None, None
        released = self.scheduler.release(rid)
        if released is None:
            return None, None
        cb = self._on_token.pop(rid, None)
        self.tracer.end(rid, "released", args={"rehomed_away": True})
        if self.flight is not None:
            self.flight.record("release", rid=rid, tenant=released.tenant)
        return released, cb

    # --- warm restart (ISSUE 18) --------------------------------------------

    def snapshot_serving_state(self) -> Dict[str, Any]:
        """Serialize the HOST-current serving state the halt contract
        defines — the queue (actives first, in slot-roll order by rid,
        exactly as a halt would requeue them), every unfinished request's
        tokens / PRNG key / deadlines / tenant+priority attribution, the
        prefix-cache token index (which prefixes were hot — an advisory
        for re-warming, NOT the KV bytes), and the SLO tracker's counters.
        No device pytrees: programs come back via the AOT cache
        (``prewarm``), KV is re-prefilled from host tokens by the same
        resume machinery preemption uses.

        The result is JSON-safe. Timestamps are absolute on THIS engine's
        clock; :meth:`restore_serving_state` shifts them onto the restored
        engine's clock so every remaining deadline budget is preserved
        across the restart."""
        active = sorted(
            (r for r in self._slot_req if r is not None and not r.finished),
            key=lambda r: r.rid,
        )
        seen = {r.rid for r in active}
        ordered = active + [
            r for r in self.scheduler.queued_requests if r.rid not in seen
        ]
        reqs = []
        for req in ordered:
            cfg = req.config
            reqs.append({
                "rid": int(req.rid),
                "prompt": [int(x) for x in req.prompt],
                "tokens": [int(t) for t in req.tokens],
                "key": [int(k) for k in np.asarray(req.key, np.uint32).reshape(-1)],
                "config": {
                    "max_new_tokens": int(cfg.max_new_tokens),
                    "temperature": float(cfg.temperature),
                    "top_k": None if cfg.top_k is None else int(cfg.top_k),
                    "top_p": None if cfg.top_p is None else float(cfg.top_p),
                    "eos_token_id": (
                        None if cfg.eos_token_id is None else int(cfg.eos_token_id)
                    ),
                },
                "tenant": req.tenant,
                "priority": req.priority,
                "preemptions": int(req.preemptions),
                "deadline": req.deadline,
                "queue_deadline": req.queue_deadline,
                "submit_time": req.submit_time,
                "admit_time": req.admit_time,
                "first_token_time": req.first_token_time,
            })
        snap: Dict[str, Any] = {
            "version": 1,
            "now": self._now(),
            "next_rid": int(self._next_rid),
            "halted": self._halted,
            "halt_reason": self._halt_reason,
            "requests": reqs,
            "prefix_index": (
                [list(e.tokens) for e in self.prefix.entries]
                if self.prefix is not None and self.prefix.enabled
                else None
            ),
            "tenant_queue_depths": self.scheduler.queued_by_tenant(),
            "slo": (
                self.metrics.slo.state() if self.metrics.slo is not None else None
            ),
        }
        if self.flight is not None:
            self.flight.record("snapshot", requests=len(reqs))
        return snap

    def restore_serving_state(self, snap: Dict[str, Any],
                              on_token=None) -> Dict[str, Any]:
        """Bring a freshly-constructed (or resumed) engine back WARM from
        :meth:`snapshot_serving_state`: every unfinished request rejoins
        the queue in snapshot order with its streamed tokens and
        host-current key (the resume machinery continues each stream
        bit-identically), and every absolute timestamp is shifted by the
        clock delta between the snapshot and now — a request that had 4s
        of deadline budget left when the replica died has exactly 4s left
        here. ``on_token`` (optional) streams every restored request.

        Raises ``ValueError`` if any snapshot rid is already known to this
        engine — restore composes with the transport's idempotency the
        same way adopt does: state is admitted exactly once."""
        if snap.get("version") != 1:
            raise ValueError(
                f"unknown serving-state snapshot version {snap.get('version')!r}"
            )
        if self._halted:
            raise RejectedError("engine is halted; cannot restore work")
        now = self._now()
        delta = now - float(snap["now"])
        for r in snap["requests"]:
            if int(r["rid"]) in self.scheduler.requests:
                raise ValueError(
                    f"rid {r['rid']} already known to this engine — "
                    "a serving-state snapshot restores exactly once"
                )

        def _shift(t):
            return None if t is None else t + delta

        restored = 0
        for r in snap["requests"]:
            req = Request(
                rid=int(r["rid"]),
                prompt=np.asarray(r["prompt"], np.int32),
                config=GenerationConfig(**r["config"]),
                key=np.asarray(r["key"], np.uint32),
                tenant=r.get("tenant", "default"),
                priority=r.get("priority", "standard"),
            )
            req.tokens = [int(t) for t in r["tokens"]]
            req.preemptions = int(r.get("preemptions", 0))
            req.deadline = _shift(r.get("deadline"))
            req.queue_deadline = _shift(r.get("queue_deadline"))
            req.submit_time = _shift(r.get("submit_time"))
            req.admit_time = _shift(r.get("admit_time"))
            req.first_token_time = _shift(r.get("first_token_time"))
            req.slot = None
            self.scheduler.submit(req)
            self.metrics.record_adopt(req, now)
            if on_token is not None:
                self._on_token[req.rid] = on_token
            self.tracer.begin(
                req.rid,
                args={
                    "prompt_len": int(len(req.prompt)),
                    "tenant": req.tenant,
                    "priority": req.priority,
                    "restored": True,
                    "tokens_streamed": len(req.tokens),
                },
            )
            restored += 1
        self._next_rid = max(self._next_rid, int(snap["next_rid"]))
        if snap.get("slo") and self.metrics.slo is not None:
            self.metrics.slo.restore_state(snap["slo"], shift_s=delta)
        downtime = max(delta, 0.0)
        self.metrics.record_restore(restored, downtime)
        if self.flight is not None:
            self.flight.record(
                "restore", requests=restored, downtime_s=downtime
            )
        if self.timeline is not None:
            self.timeline.instant(
                "restore", "serving", args={"requests": restored}
            )
        self._sync_health()
        return {
            "restored": restored,
            "downtime_s": downtime,
            "rid_floor": int(self._next_rid),
        }

    # --- health / drain -----------------------------------------------------

    def integrity_fingerprint(self) -> int:
        """Bit-level uint32 fingerprint of this replica's PARAMS — the
        router's cross-replica integrity evidence (ISSUE 20). Params only,
        deliberately: replicas serving the same model must hold
        bit-identical weights, while KV/slot state legitimately diverges
        with each replica's traffic (KV integrity is covered separately,
        by per-page reuse validation and the page-quarantine path). The
        jitted reduction compiles on FIRST probe (lazy — un-probed engines
        compile nothing); the readback is one uint32 scalar per probe
        period, never per chunk."""
        if self._integrity_fp_fn is None:
            from neuronx_distributed_tpu.utils.fingerprint import (
                tree_fingerprint,
            )

            # per-engine lambda (see the _extract_fn note: jitting the
            # module-level helper directly would share _cache_size across
            # engines in this jax)
            self._integrity_fp_fn = self.programs.wrap(
                "integrity_fingerprint",
                jax.jit(lambda tree: tree_fingerprint(tree)),
            )
        # graftlint: ok[GL02] periodic watchdog probe readback — one uint32
        # scalar per probe period (router cadence), not a per-chunk sync
        return int(jax.device_get(self._integrity_fp_fn(self._params)))

    def health(self) -> EngineHealth:
        """Current health state (``OK/DEGRADED/DRAINING/HALTED``)."""
        if self._halted:
            return EngineHealth.HALTED
        if self._draining:
            return EngineHealth.DRAINING
        if (
            self.cache.usable_slots < self.num_slots
            or getattr(self.cache, "degraded", False)
            or (
                self._had_dispatch_failure
                and self._chunks_since_failure < self._degraded_cooldown
            )
        ):
            return EngineHealth.DEGRADED
        return EngineHealth.OK

    @property
    def halt_reason(self) -> Optional[str]:
        return self._halt_reason

    def drain(self) -> None:
        """Stop admitting NEW work: submissions are rejected, never-admitted
        queued requests stay queued (and stop counting as work), requests
        already admitted — active in a slot or preempted back to the queue —
        run to completion. ``run()`` returns once in-flight work finishes."""
        self._draining = True
        if self.timeline is not None:
            self.timeline.instant("drain", "serving")
        self._sync_health()

    def resume(self) -> None:
        """Leave DRAINING and accept work again (no-op while HALTED)."""
        self._draining = False
        self._sync_health()

    def fence(self, reason: str = "fenced") -> None:
        """Operator/watchdog kill switch: take the engine to HALTED *now*
        through the standard halt contract — in-flight work is vacated
        with host-current tokens/keys and requeued (never stranded), the
        post-mortem flight dump is written, and ``run()`` stops making
        progress. The router's watchdog calls this when a replica is
        declared dead so its queue can be re-homed or snapshot-restored;
        idempotent on an already-halted engine."""
        if self._halted:
            return
        self._halt(f"fenced: {reason}")

    def _halt(self, reason: str) -> None:
        # the HALTED contract: in-flight work is REQUEUED, never stranded.
        # The dispatch-recovery and quarantine paths vacate their slots
        # before halting (this is a no-op there); a prefill-failure halt
        # reaches here with requests still actively decoding — push them
        # back to the queue with their host-current tokens/keys so an
        # operator handing off scheduler.requests loses nothing
        requeued = self._vacate_active()
        if requeued:
            self.scheduler.requeue_front(requeued)
            self.cache.release_all_slots()
            self.cache.reset()
            if self.draft_cache is not None:
                self.draft_cache.release_all_slots()
                self.draft_cache.reset()
            self._state = self._fresh_slot_state()
        if self.prefix is not None:
            # PR 3 recovery contract, prefix edition: no in-flight suffix
            # prefill survives a halt, so no pin may either — a leaked ref
            # would block eviction forever. Entries themselves stay valid
            # (independent copies, untouched by cache loss)
            self.prefix.release_all()
        self._halted = True
        self._halt_reason = reason
        if self.timeline is not None:
            self.timeline.instant("halted", "serving", args={"reason": reason})
        if self._profiling:
            # never leave a device trace open across a halt — the window
            # below [2, 5) can only close here once the loop stops
            self._stop_profile()
        self._sync_health()
        # post-mortem: the flight ring (recent transitions/faults) plus the
        # metrics snapshot, written atomically BEFORE control returns to the
        # operator; the timeline flushes too so the trace survives a crash
        if self.flight is not None:
            self.flight.record("halt", reason=reason)
            # who was being starved when the engine died: per-tenant queue
            # depths AFTER the requeue (so in-flight victims count), plus
            # the SLO attainment state — kept FLAT enough that the flight
            # recorder's depth-capped redaction preserves every scalar
            # (tests/observability/test_flight_recorder.py pins the schema)
            # analyze_programs=False: an error path must not start
            # tracing programs for cost analysis — and the nested
            # efficiency blocks are dropped from the embedded snapshot
            # (the depth-3 redaction would collapse them to key-count
            # stubs anyway; the FLAT tables below are the readable
            # carriers)
            metrics_snap = self.metrics.snapshot(analyze_programs=False)
            metrics_snap.pop("programs", None)
            metrics_snap.pop("hbm", None)
            extra = {
                "requeued": len(requeued),
                "metrics": metrics_snap,
                "tenant_queue_depths": self.scheduler.queued_by_tenant(),
                # where HBM actually went and which programs were hot when
                # the engine died — flat scalar tables shaped to survive
                # the flight recorder's depth-3 redaction
                "hbm": self.hbm.halt_summary(),
                "programs": self.programs.halt_summary(),
            }
            if self.tier is not None:
                # where the spill tier stood when the engine died — flat
                # scalars (occupancy + lifetime traffic), same redaction
                # contract as the hbm/programs tables above
                extra["kv_host_tier"] = self.tier.summary()
            if self.metrics.slo is not None:
                extra["slo"] = self.metrics.slo.per_tenant()
                extra["slo_totals"] = self.metrics.slo.totals()
            self.flight.dump(reason, extra=extra)
        if self.timeline is not None:
            self.timeline.save()

    def _sync_health(self) -> None:
        h = self.health()
        self.metrics.health = h.value
        if h is not self._last_health:
            if self.timeline is not None:
                self.timeline.instant(f"health {h.value}", "serving")
            if self.flight is not None:
                self.flight.record(
                    "health", value=h.value, was=self._last_health.value
                )
            self._last_health = h

    @property
    def has_work(self) -> bool:
        if self._halted:
            # requeued work survives in the queue for inspection/handoff,
            # but a halted engine makes no progress — run() must exit
            return False
        if self._draining:
            return any(self._active) or any(
                r.admit_time is not None
                for r in self.scheduler.queued_requests
            )
        return self.scheduler.queued > 0 or any(self._active)

    @property
    def queue_depth(self) -> int:
        """Queued (unfinished) requests — the export-gauge source."""
        return self.scheduler.queued

    @property
    def decode_compilations(self) -> int:
        """How many distinct decode programs XLA compiled. Stays 1 across
        arbitrary slot churn AND arbitrary per-slot acceptance patterns —
        the continuous-batching invariant (one program per engine, whatever
        the chunk size or gamma; ragged speculative advance is data, not
        shape). A speculative engine that ever exercised the
        non-speculative fallback counts that program too (so the invariant
        is 1 on any fault-free run)."""
        n = 0
        if self._decode_chunk is not None:
            n += int(self._decode_chunk._cache_size())
        if self._spec_chunk is not None:
            n += int(self._spec_chunk._cache_size())
        return n

    @property
    def prefill_compilations(self) -> int:
        """How many distinct prefill programs XLA compiled — full prefills
        (one per padded ``_bucket`` length actually used, target plus
        draft), plus suffix prefills (one per ``_suffix_bucket`` chunk
        length), so growth is bounded by the bucket counts (powers of two
        plus exact fallbacks), never by request count or prefix-cache
        churn."""
        return (
            sum(int(fn._cache_size()) for fn in self._prefill_fns.values())
            + sum(
                int(fn._cache_size())
                for fn in self._draft_prefill_fns.values()
            )
            + int(self._suffix_fn._cache_size())
        )

    @property
    def prefix_compilations(self) -> int:
        """Prefix-cache maintenance programs XLA compiled (extract + seed,
        one per storage bucket; fingerprint, one per entry shape) — bounded
        by the ``_prefix_bucket`` count. Paged engines count their
        seed-from-pages programs instead (one per shared page count)."""
        return sum(
            int(fn._cache_size())
            for fn in (self._extract_fn, self._seed_fn, self._fingerprint_fn)
        ) + getattr(self.cache, "seed_compilations", 0)

    # --- AOT serving (inference/aot.py) ----------------------------------

    def manifest(self):
        """:class:`~..inference.aot.ProgramManifest` of every program this
        engine has compiled so far — the prewarm input for the NEXT
        process (persist it next to the checkpoint via ``.save(dir)``)."""
        return self.programs.manifest()

    # manifest program name → engine attribute, for resolve and install
    _AOT_FIXED = {
        "decode_chunk": "_decode_chunk",
        "spec_decode_chunk": "_spec_chunk",
        "slot_write": "_slot_write",
        "slot_clear": "_slot_clear",
        "first_token": "_first_token",
        "suffix_prefill": "_suffix_fn",
        "prefix_extract": "_extract_fn",
        "prefix_seed": "_seed_fn",
        "prefix_fingerprint": "_fingerprint_fn",
    }
    # cache-manager program stem → manager attribute (Slot + Paged)
    _AOT_CACHE = {
        "cache_admit": "_admit_fn", "cache_free": "_free_fn",
        "cache_reset": "_reset_fn",
        "paged_admit": "_admit_fn", "paged_seed": "_seed_fn",
        "paged_free": "_free_fn", "paged_reset": "_reset_fn",
        "paged_stage": "_stage_fn", "paged_map": "_map_fn",
        "paged_import": "_import_fn",
    }

    def _aot_cache_site(self, name: str):
        """(manager, attr) for a cache-manager program name, else None."""
        mgr, stem = self.cache, name
        if name.startswith("draft_"):
            mgr, stem = self.draft_cache, name[len("draft_"):]
        attr = self._AOT_CACHE.get(stem)
        if mgr is None or attr is None or not hasattr(mgr, attr):
            return None
        return mgr, attr

    def _aot_resolve(self, name: str):
        """Live ledger proxy for a manifest program name — building lazy
        per-bucket programs on demand. None when this engine cannot host
        the program (e.g. a draft program on a non-speculative engine)."""
        attr = self._AOT_FIXED.get(name)
        if attr is not None:
            fn = getattr(self, attr, None)
            if fn is None and name == "decode_chunk" and self._spec_chunk is not None:
                # speculative engine: the plain-chunk fallback is built
                # lazily — a manifest that saw it means prewarm should too
                fn = self._nonspec_chunk()
            return fn
        m = re.fullmatch(r"(draft_)?prefill\[(\d+)\]", name)
        if m is not None:
            try:
                if m.group(1):
                    return self._draft_prefill_fn(int(m.group(2)))
                return self._prefill_fn(int(m.group(2)))
            except Exception:
                return None
        site = self._aot_cache_site(name)
        if site is not None:
            return getattr(site[0], site[1])
        return None

    def _aot_install(self, name: str, shim) -> bool:
        """Install a deserialized-executable shim at the program's
        dispatch site, re-wrapped by the ledger so counting survives."""
        wrapped = self.programs.wrap(name, shim)
        attr = self._AOT_FIXED.get(name)
        if attr is not None:
            setattr(self, attr, wrapped)
            return True
        m = re.fullmatch(r"(draft_)?prefill\[(\d+)\]", name)
        if m is not None:
            fns = (
                self._draft_prefill_fns if m.group(1) else self._prefill_fns
            )
            fns[int(m.group(2))] = wrapped
            return True
        site = self._aot_cache_site(name)
        if site is not None:
            setattr(site[0], site[1], wrapped)
            return True
        return False

    def prewarm(self, manifest=None, cache_dir: Optional[str] = None,
                mode: str = "auto") -> dict:
        """Restore or compile the full program set BEFORE the first
        request — bucket prefills, decode/spec chunks, slot write/clear,
        paged admit/seed/stage/map — so the first request's TTFT contains
        zero compiles and ``decode_compilations`` stays 1 (or 0 when the
        decode chunk deserialized). ``manifest`` is a
        :class:`~..inference.aot.ProgramManifest` or a path; with
        ``cache_dir`` alone the manifest is read from
        ``cache_dir/manifest.json``, serialized executables from
        ``cache_dir/*.aotx``, and the persistent compile cache is pointed
        at ``cache_dir/xla``. ``mode="trace"`` skips executable artifacts
        (pure replay prewarm). Fail-soft throughout: skew, unportable, or
        unresolvable entries degrade to the next rung with a flight
        event; returns the per-program report."""
        from neuronx_distributed_tpu.inference import aot

        if cache_dir is not None:
            aot.enable_persistent_cache(os.path.join(cache_dir, aot.XLA_SUBDIR))
        if manifest is None:
            if cache_dir is None:
                raise ValueError("prewarm needs a manifest or a cache_dir")
            manifest = aot.ProgramManifest.load(cache_dir)
        elif isinstance(manifest, (str, os.PathLike)):
            manifest = aot.ProgramManifest.load(os.fspath(manifest))
        report = aot.prewarm_programs(
            manifest,
            self._aot_resolve,
            ledger=self.programs,
            artifact_dir=cache_dir,
            install=self._aot_install,
            mode=mode,
            flight=self.flight,
        )
        return report

    def save_aot(self, cache_dir: str) -> dict:
        """Persist this engine's full AOT bundle into ``cache_dir``:
        ``manifest.json``, one serialized executable per captured program
        signature, and the persistent compile cache under ``xla/`` (so a
        later trace-level prewarm against this dir is all disk hits). The
        per-program ``lower().compile()`` each serialization needs runs
        with the disk cache BYPASSED — a cache-loaded executable
        serializes without its object code and cannot cross a process
        boundary (aot.serializable_compiles). Per-program failures are
        skipped and reported, never raised."""
        from neuronx_distributed_tpu.inference import aot

        os.makedirs(cache_dir, exist_ok=True)
        aot.enable_persistent_cache(os.path.join(cache_dir, aot.XLA_SUBDIR))
        manifest = self.manifest()
        # merge-don't-clobber: a PREWARMED engine's ledger has no captured
        # signatures for deserialized programs (they never compiled here),
        # so a blind overwrite would erase the very entries the next
        # process needs; keep prior entries for programs this run can't
        # re-describe
        try:
            prior = aot.ProgramManifest.load(cache_dir)
            for pname, entries in prior.programs.items():
                manifest.programs.setdefault(pname, entries)
        except Exception:
            pass
        manifest.save(cache_dir)
        report: Dict[str, Any] = {"saved": [], "skipped": {}}
        for name, info in self.programs.programs().items():
            for var in info.variants:
                key = (
                    f"{name}@{var.signature}"
                    if len(info.variants) > 1 else name
                )
                try:
                    lowered = var.lower()
                    if lowered is None:
                        report["skipped"][key] = "signature not captured"
                        continue
                    # bypass the disk cache for THIS compile: a cache-hit
                    # executable serializes without object code and fails
                    # cross-process (see aot.serializable_compiles)
                    with aot.serializable_compiles():
                        compiled = lowered.compile()
                    aot.save_executable(
                        cache_dir, name, var.signature, compiled
                    )
                    report["saved"].append(key)
                except Exception as e:
                    report["skipped"][key] = (
                        f"{type(e).__name__}: {e}"[:200]
                    )
        if self.flight is not None:
            self.flight.record(
                "aot_save", dir=cache_dir,
                saved=len(report["saved"]), skipped=len(report["skipped"]),
            )
        return report

    def step(self) -> bool:
        """One engine iteration: reap cancellations → shed expired deadlines
        → preempt/rewind if the cursor is out of room → admit+prefill → one
        fused decode chunk (with recovery) → retire finished slots. Returns
        whether work remains."""
        if self._halted:
            return self.has_work
        if self._faults is not None:
            # chaos (ISSUE 20): a scheduled silent bit flip lands on the
            # bound weights here — every program after this step serves
            # from the corrupted tree, exactly like real HBM rot, until
            # the router's fingerprint vote fences this replica
            self._params = self._faults.on_engine_params(
                self._steps_seen, self._params
            )
        self._steps_seen += 1
        now = self._now()
        self._reap_cancelled(now)
        self._shed_expired(now)
        # one more dispatch needs _round_cols columns (gamma per
        # speculative round, 1 per plain step); preempt-and-rewind when the
        # wall is closer than that. Speculation spends columns faster than
        # tokens (rejected drafts leave gap columns), so this wall can
        # arrive earlier than the token-based admission projected — the
        # preemption machinery keeps streams bit-identical either way
        if any(self._active) and (
            self.cache.cursor + self._round_cols > self.max_seq_len
        ):
            self._preempt_all()
        if not any(self._active) and self.cache.cursor > 0:
            # drained: rewind the shared cursor so the next wave starts at
            # column 0 (storage reused, nothing reallocated)
            self.cache.reset()
            if self.draft_cache is not None:
                self.draft_cache.reset()
        # SLO-driven preemption (ISSUE 16): when the slot set is full and
        # an under-attaining tenant's work is waiting, the policy may
        # nominate victims (FIFO never does) — vacated through the same
        # host bookkeeping as quarantine-requeue, so streams stay
        # bit-identical and the freed slots admit below in THIS step
        victims = self.policy.victims(now)
        if victims:
            self._preempt_victims(victims, now)
        self._admit(now)
        if not self._halted and any(self._active):
            self._decode()
        if self.timeline is not None:
            self.timeline.counter("slots_active", int(self._active.sum()), "serving")
            self.timeline.counter("queue_depth", self.scheduler.queued, "serving")
        if self._profiling and not self.has_work:
            # a short run can drain before the [2, 5) window's closing
            # chunk — flush the device trace rather than dropping it
            self._stop_profile()
        self._sync_health()
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        """Step until idle (or HALTED); returns every request this engine
        has seen."""
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return {r.rid: r for r in self.scheduler.requests.values()}

    # --- deadlines ----------------------------------------------------------

    def _shed_expired(self, now: float) -> None:
        """Queue timeouts shed BEFORE prefill (no compute wasted on a
        request that already missed its window); in-flight deadlines are
        enforced here, at the chunk boundary — the shed request keeps every
        token it streamed."""
        for req, reason in self.scheduler.expire(now):
            req.state = RequestState.TIMED_OUT
            req.error = reason
            req.finish_time = now
            self.metrics.record_shed(req, now, where="queue")
            self._on_token.pop(req.rid, None)
            if self.timeline is not None:
                self.timeline.instant(
                    f"shed r{req.rid}", "serving",
                    args={"where": "queue", "reason": req.error},
                )
            self.tracer.end(
                req.rid, "shed",
                args={"where": "queue", "tenant": req.tenant},
            )
            if self.flight is not None:
                self.flight.record("shed", rid=req.rid, where="queue",
                                   reason=req.error, tenant=req.tenant)
        for req in list(self._slot_req):
            if req is None or req.deadline is None or now < req.deadline:
                continue
            req.state = RequestState.TIMED_OUT
            req.error = "deadline exceeded mid-generation"
            req.finish_time = now
            self.metrics.record_shed(req, now, where="inflight")
            if self.timeline is not None:
                self.timeline.instant(
                    f"shed r{req.rid}", "serving",
                    args={"where": "inflight", "tokens": len(req.tokens)},
                )
            self.tracer.end(
                req.rid, "shed",
                args={"where": "inflight", "tokens": len(req.tokens),
                      "tenant": req.tenant},
            )
            if self.flight is not None:
                self.flight.record("shed", rid=req.rid, where="inflight",
                                   tokens=len(req.tokens),
                                   tenant=req.tenant)
            self._release_slot(req)

    # --- admission ----------------------------------------------------------

    def _in_flight_tokens(self) -> int:
        return sum(
            r.token_footprint for r in self._slot_req if r is not None
        )

    def _admit(self, now: float) -> None:
        if self.external_prefill:
            return  # the disaggregation server owns admission
        if self.cache.free_slots == 0 or self.scheduler.queued == 0:
            return
        # tiered KV (ISSUE 19): start host->device prefetches for queued
        # requests whose prefix match is host-resident BEFORE selection —
        # the async import dispatch overlaps the current chunk's device
        # time, and the pages' prefetch holds keep the fit math below
        # honest (held pages are not reclaimable)
        self._prefetch_for_queue()
        proj = self.cache.cursor
        maxrem = max(
            (r.remaining_new_tokens for r in self._slot_req if r is not None),
            default=0,
        )

        # paged: the free-page accounting that replaces seq-len-class-only
        # gating — per-slot context starts feed the worst-case page spans
        spans_starts = (
            list(self.cache.active_spans())
            if self._page_size is not None else []
        )
        eager_claimed = 0

        def fits(req: Request) -> bool:
            nonlocal proj, maxrem, eager_claimed
            if self._draining and req.admit_time is None:
                # drain admits only work that was already in flight once
                # (preempted/recovered requests rejoin at the queue FRONT,
                # so fresh requests behind them cannot starve them)
                return False
            p = len(req.context_ids)
            # the padded prompt must leave room for the remaining
            # generation AND (speculative engines) the final round's
            # gamma-token window — _round_cols - 1 == 0 on the plain path
            if self._page_size is not None:
                bucket, target = self._paged_layout(
                    p, req.remaining_new_tokens + self._round_cols - 1, proj
                )
            else:
                bucket = _bucket(
                    p, self.max_seq_len,
                    req.remaining_new_tokens + self._round_cols - 1,
                )
                target = max(proj, bucket)
            if self.admission == "conservative":
                # all slots step together, so the cursor's final resting
                # place is the admission cursor plus the LONGEST remaining
                # generation in flight — a long prompt's cursor jump must
                # not strand the slots already running (they'd hit the
                # preemption wall conservative mode promises to avoid).
                # Speculation is TOKEN-optimistic here: a column costs one
                # token only when accepted, so a poor-acceptance run can
                # still hit the preempt-rewind wall (documented trade; the
                # all-accept case matches this projection exactly)
                if (
                    target + max(maxrem, req.remaining_new_tokens)
                    + self._round_cols - 1
                    > self.max_seq_len
                ):
                    return False
                if self._page_size is not None:
                    # every in-flight + selected context's pages through
                    # the projected final cursor must fit the pool (shared
                    # pages double-counted, early retirement ignored —
                    # strictly conservative, so the no-preemption promise
                    # extends to the page-pressure wall)
                    t_end = min(
                        self.max_seq_len,
                        target + max(maxrem, req.remaining_new_tokens)
                        + self._round_cols - 1,
                    )
                    spans = self.cache.page_span(target - p, t_end) + sum(
                        self.cache.page_span(s, t_end) for s in spans_starts
                    )
                    if spans > self.cache.alloc.capacity:
                        return False
            else:
                if target + self._round_cols > self.max_seq_len:
                    # eager: just the prefill + one decode round must fit;
                    # the preemption path recovers the rest
                    return False
                if self._page_size is not None:
                    # eager page gate: this round's prefill pages plus one
                    # decode window (clamped to the request's remaining
                    # work, matching _ensure_decode_pages — an unclamped
                    # window would starve short-tail requests a small pool
                    # can in fact serve), against what the pool can free
                    # up (reclaimable prefix entries included)
                    window = (
                        min(
                            self.decode_chunk_size,
                            max(req.remaining_new_tokens, 1),
                        ) * self._round_cols
                    )
                    need = self.cache.page_span(
                        target - p,
                        min(self.max_seq_len, target + window),
                    )
                    # in-flight prefetches (ISSUE 19): pages prefetched
                    # FOR THIS REQUEST are device-resident under a hold —
                    # excluded from available_pages() so the reclaim
                    # valve cannot spill them back out, yet still counted
                    # in ``need`` (the span covers the matched prefix's
                    # columns). Credit them here or a tight pool
                    # livelocks: the hold depresses availability below a
                    # bar the adoption path never actually has to clear
                    avail = self.cache.available_pages()
                    if self.prefix is not None:
                        peeked = self.prefix.peek(req.context_ids)
                        if (
                            peeked is not None
                            and peeked[0].page_ids
                            and self.cache.prefetch_held(
                                peeked[0].page_ids
                            )
                        ):
                            avail += len(peeked[0].page_ids)
                    if eager_claimed + need > avail:
                        return False
                    eager_claimed += need
            proj = target
            maxrem = max(maxrem, req.remaining_new_tokens)
            if self._page_size is not None:
                spans_starts.append(target - p)
            return True

        cost = None
        if self.prefix is not None:
            # effective prefill work: context minus the reusable prefix (a
            # read-only peek — no LRU state moves until the real lookup).
            # Longest-EFFECTIVE-prefill-first keeps the overlap rationale
            # when a long shared context is actually a cheap suffix
            def cost(req: Request) -> int:
                return len(req.context_ids) - self.prefix.match_len(
                    req.context_ids
                )

        selected = self.scheduler.select(
            self.cache.free_slots, self._in_flight_tokens(), fits,
            prefill_cost=cost, now=now,
        )
        for idx, req in enumerate(selected):  # longest-prefill-first
            self._prefill_into_slot(req, self.cache.acquire(), now)
            if self._halted:
                # a prefill-failure halt mid-batch: the rest of this round
                # was already popped from the queue — put it back intact
                rest = selected[idx + 1:]
                if rest:
                    self.scheduler.requeue_front(rest)
                break

    def _prefill_fn(self, padded_len: int):
        fn = self._prefill_fns.get(padded_len)
        if fn is None:
            prefill = self._prefill_model

            @jax.jit
            def fn(params, ids, mask):
                out, variables = prefill.apply(
                    params, ids, padding_mask=mask, mutable=["cache"]
                )
                return unwrap_logits(out)[0, -1], variables["cache"]

            fn = self.programs.wrap(
                f"prefill[{padded_len}]", self._comms_scoped(fn)
            )
            self._prefill_fns[padded_len] = fn
        return fn

    def _draft_prefill_fn(self, padded_len: int):
        fn = self._draft_prefill_fns.get(padded_len)
        if fn is None:
            prefill = self._draft_prefill_model

            @jax.jit
            def fn(params, ids, mask):
                _, variables = prefill.apply(
                    params, ids, padding_mask=mask, mutable=["cache"]
                )
                return variables["cache"]

            fn = self.programs.wrap(
                f"draft_prefill[{padded_len}]", self._comms_scoped(fn)
            )
            self._draft_prefill_fns[padded_len] = fn
        return fn

    def _prefill_into_slot(self, req: Request, slot: int, now: float) -> None:
        ctx = req.context_ids
        p = len(ctx)
        target = None
        if self._page_size is not None:
            padded, target = self._paged_layout(
                p, req.remaining_new_tokens + self._round_cols - 1,
                self.cache.cursor,
            )
        else:
            padded = _bucket(
                p, self.max_seq_len,
                req.remaining_new_tokens + self._round_cols - 1,
            )
        self.tracer.step(req.rid, "admission", args={"slot": slot})
        plan = self._plan_prefix_reuse(ctx, p, padded)
        self.tracer.step(
            req.rid, "prefix_lookup",
            args={"matched": plan[1] if plan is not None else 0},
        )
        if self.timeline is not None:
            self.timeline.mark_event_start("prefill", "serving")
        call = self._prefill_calls
        self._prefill_calls += 1
        t0 = self._clock()
        try:
            try:
                if self._faults is not None:
                    self._faults.on_prefill(call)
                if plan is not None:
                    entry, m_use, chunk = plan
                    s = p - m_use
                    if self._page_size is not None:
                        # ZERO-COPY seed: gather the entry's shared pool
                        # pages into the compute view directly — no entry
                        # copy exists, no page is allocated or written
                        row = self.cache.seed_row(
                            entry.page_ids[:m_use // self._page_size],
                            m_use, padded - p,
                        )
                    else:
                        # seed a fresh row from the stored prefix COPY (the
                        # entry is pinned, read, never aliased or donated),
                        # then prefill only the uncached tail through the
                        # decode-mode cache-write path at the prefix's cursor
                        row = self._seed_fn(
                            entry.tree,
                            jnp.asarray(m_use, jnp.int32),
                            jnp.asarray(padded - p, jnp.int32),
                            self.max_seq_len,
                        )
                    sfx_ids, _ = pack_padded_prompt(
                        ctx[m_use:], chunk, pad_side="right"
                    )
                    logits, row_cache = self._suffix_fn(
                        self._params, row, jnp.asarray(sfx_ids),
                        jnp.asarray(s, jnp.int32),
                    )
                else:
                    ids, mask = pack_padded_prompt(ctx, padded)
                    logits, row_cache = self._prefill_fn(padded)(
                        self._params, jnp.asarray(ids), jnp.asarray(mask)
                    )
                if self.draft_model is not None:
                    # the draft context ALWAYS full-prefills (a target
                    # prefix hit composes with it untouched: prefix
                    # entries hold target KV only — the draft is cheap by
                    # construction, so deduping its prefill is not worth a
                    # second store). No readback: the row is consumed by
                    # the donating draft admit below, zero added syncs
                    d_ids, d_mask = pack_padded_prompt(ctx, padded)
                    draft_row = self._draft_prefill_fn(padded)(
                        self._draft_params,
                        jnp.asarray(d_ids), jnp.asarray(d_mask),
                    )
            finally:
                if plan is not None:
                    self.prefix.release(plan[0])
        except Exception as e:
            # an OOM-like prefill fault fails ONE request for cause instead
            # of crashing the loop; the slot returns to the rotation.
            # Consecutive failures across requests are bounded like the
            # dispatch path: a persistently-failing prefill (bad weights
            # after a hot swap, real OOM) must not silently fail 100% of
            # traffic while health() reads OK
            if self.timeline is not None:
                self.timeline.mark_event_end(
                    "prefill", "serving", args={"rid": req.rid, "error": str(e)}
                )
                self.timeline.instant(
                    f"prefill_failure r{req.rid}", "serving",
                    args={"error": str(e)[:200]},
                )
            self.cache.free(slot)
            req.state = RequestState.FAILED
            req.error = f"prefill failed: {e}"
            req.finish_time = now
            self.metrics.record_failed(req, now, kind="prefill")
            self.tracer.end(req.rid, "failed", args={"kind": "prefill"})
            if self.flight is not None:
                self.flight.record("prefill_failure", rid=req.rid,
                                   error=str(e), tenant=req.tenant)
            self._on_token.pop(req.rid, None)
            self._consecutive_prefill_failures += 1
            if (
                self._consecutive_prefill_failures
                >= self._dispatch_retry.max_attempts
            ):
                self._halt(
                    f"{self._consecutive_prefill_failures} consecutive "
                    f"prefill failures (last: {type(e).__name__}: {e})"
                )
            return
        self._consecutive_prefill_failures = 0
        self.metrics.record_prefill_wall(
            self._clock() - t0, kind="suffix" if plan is not None else "full"
        )
        if self.timeline is not None:
            self.timeline.mark_event_end(
                "prefill", "serving",
                args={
                    "rid": req.rid, "padded": padded,
                    "reused": plan[1] if plan is not None else 0,
                },
            )
        self.tracer.step(
            req.rid,
            "suffix_prefill" if plan is not None else "full_prefill",
            args={"padded": padded,
                  "reused": plan[1] if plan is not None else 0},
        )
        if self._page_size is None:
            self._remember_prefix(
                ctx, p, padded, row_cache,
                matched=plan[1] if plan is not None else 0,
            )
            self.cache.admit(row_cache, slot, padded)
            if self.draft_model is not None:
                # mirror the slot into the draft cache at the SAME cursor
                # the target admit just set — the two cursors stay in
                # lockstep, so every speculative round's windows line up
                # column-for-column
                self.draft_cache.admit(
                    draft_row, slot, padded, cursor=self.cache.cursor
                )
        else:
            from neuronx_distributed_tpu.serving.paging import PageExhausted

            m_shared = plan[1] if plan is not None else 0
            shared = (
                plan[0].page_ids[:m_shared // self._page_size]
                if plan is not None else ()
            )
            try:
                self.cache.admit(
                    row_cache, slot, padded, cursor=target, p=p,
                    shared_ids=shared, m_shared=m_shared,
                )
                if self.draft_model is not None:
                    # the draft twin maps its own pool pages at the same
                    # aligned cursor (it always full-prefills — no sharing)
                    self.draft_cache.admit(
                        draft_row, slot, padded, cursor=self.cache.cursor,
                        p=p,
                    )
            except PageExhausted as e:
                # eager-mode page pressure between fits() and admit (e.g. a
                # reclaim raced dry): nothing is mapped — put the slot and
                # the untouched request back; the wall/preempt machinery
                # owns the rest
                self.cache.free(slot)
                if self.draft_cache is not None:
                    self.draft_cache.free(slot)
                self.scheduler.requeue_front([req])
                if self.flight is not None:
                    self.flight.record("page_exhausted", rid=req.rid,
                                       error=str(e))
                return
            if m_shared:
                self.metrics.record_prefix_pages_shared(
                    m_shared // self._page_size
                )
            self._remember_prefix_paged(ctx, p, slot, matched=m_shared)
        self._bind_slot(req, slot, logits, now)

    def _bind_slot(self, req: Request, slot: int, logits, now: float) -> None:
        """The admission tail shared by coupled prefill and the
        disaggregated page-table handoff: record the admit, sample the
        first token off ``logits`` (fresh requests only — one explicit
        device_get of the token+key pair), and activate the slot's
        device-resident state."""
        self.metrics.record_admit(req, now)
        if req.admit_time is None:
            req.admit_time = now
        if not req.tokens:
            # fresh request: sample the first token exactly as generate()
            # does — split the request key, sample with the sub-key. The
            # sampled token and the advanced key ride ONE explicit
            # device_get (they used to be two implicit syncs — an int()
            # coercion plus an np.asarray — which GL02 now forbids;
            # tests/serving/test_host_sync.py pins the count at 1)
            carry, sub = jax.random.split(jnp.asarray(req.key))
            temp, topk, topp = _config_sentinels(req.config)
            # graftlint: ok[GL02] the admission path's single documented
            # sync: first token + advanced request key in one readback
            tok0_h, carry_h = jax.device_get(
                (self._first_token(logits, sub, temp, topk, topp), carry)
            )
            tok0 = int(tok0_h)
            req.key = np.asarray(carry_h, np.uint32)
            self.tracer.step(req.rid, "first_token")
            self._emit_token(req, tok0, now, first=True)
            if req.state is RequestState.CANCELLED:
                # the on_token callback cancelled on the FIRST token (while
                # req.slot was still None, so cancel() already recorded it):
                # the slot was acquired but never bound — free it and stop
                # before the DECODE transition would erase the cancellation
                req.finish_time = now
                self.cache.free(slot)
                self._on_token.pop(req.rid, None)
                return
        req.state = RequestState.DECODE
        req.slot = slot
        self._slot_req[slot] = req
        temp, topk, topp = _config_sentinels(req.config)
        self._state = self._slot_write(
            self._state,
            np.int32(slot),
            np.int32(req.tokens[-1]),
            jnp.asarray(req.key),
            temp, topk, topp,
            np.int32(req.remaining_new_tokens),
            np.int32(
                req.config.eos_token_id
                if req.config.eos_token_id is not None
                else -1
            ),
        )
        self._active[slot] = True
        # a request can be born finished (max_new_tokens == 1, or EOS as
        # its very first token) — retire before it ever decodes
        self._maybe_finish(req, now)

    def admit_staged(self, req: Request, staged, logits,
                     now: Optional[float] = None) -> bool:
        """Disaggregated handoff (ISSUE 14): bind an EXTERNALLY-prefilled
        context to a slot as a PAGE-TABLE operation. ``staged`` is a
        :class:`~neuronx_distributed_tpu.serving.paging.StagedContext`
        whose pages already live in THIS engine's pool (the prefill worker
        staged them there — shared-pool handoff moves zero KV bytes,
        ``PageAllocator.copy_bytes`` untouched; a distinct-pool worker
        routes through export/import first). ``logits`` is the prefill's
        last-token logits row (fresh requests sample their first token
        here, exactly like coupled admission — streams stay bit-identical).

        Returns False — staged context intact, request untouched — when
        the slot/cursor/page accounting cannot place it RIGHT NOW (no free
        slot, conservative-cursor overflow, page-span overflow); the
        caller retries at a later chunk boundary."""
        if self._page_size is None:
            raise ValueError(
                "admit_staged needs a paged engine (kv_page_size=) — the "
                "handoff is a block-table operation"
            )
        if self._halted:
            return False
        if not self.cache.staged_live(staged):
            # a pool recovery or page quarantine between prefill and
            # handoff voided the staged pages — fail loudly so the caller
            # re-prefills (returning False would retry a dead context
            # forever)
            raise ValueError(
                "staged context is no longer live (pool recovery or page "
                "quarantine voided it) — re-prefill"
            )
        if self.cache.free_slots == 0:
            return False
        now = self._now() if now is None else now
        p = staged.p
        rem = req.remaining_new_tokens
        maxrem = max(
            (r.remaining_new_tokens for r in self._slot_req if r is not None),
            default=0,
        )
        target = self.cache.aligned_target(max(self.cache.cursor, p), p)
        end = target + max(maxrem, rem) + self._round_cols - 1
        if end > self.max_seq_len:
            return False
        # conservative page projection, exactly the coupled fits() math:
        # every in-flight context's span plus the staged one through the
        # projected final cursor must fit the pool
        t_end = min(self.max_seq_len, end)
        spans = self.cache.page_span(target - p, t_end) + sum(
            self.cache.page_span(s, t_end)
            for s in self.cache.active_spans()
        )
        if spans > self.cache.alloc.capacity:
            return False
        slot = self.cache.acquire()
        try:
            self.cache.map_staged(slot, staged, cursor=target)
        except Exception:
            # nothing mapped — the slot must rejoin the rotation, or each
            # failed handoff would permanently shrink capacity
            self.cache.free(slot)
            raise
        self.tracer.step(
            req.rid, "admission",
            args={"slot": slot, "handoff": "page_table", "pages": len(
                staged.page_ids
            )},
        )
        if self.timeline is not None:
            self.timeline.instant(
                f"handoff r{req.rid}", "serving",
                args={"slot": slot, "pages": len(staged.page_ids)},
            )
        self._bind_slot(req, slot, logits, now)
        return True

    # --- prefix reuse -------------------------------------------------------

    def _plan_prefix_reuse(self, ctx, p: int, padded: int):
        """Admission-time prefix lookup. Returns ``(entry, m_use, chunk)``
        for a validated hit — ``entry`` PINNED (the caller releases it when
        the suffix prefill settles, success or failure) — or ``None`` for
        a miss, a match below ``min_match``, or an entry that failed its
        reuse-time checksum/shape validation (evicted on the spot and the
        admission falls back to the full prefill: poisoned KV never
        reaches a slot)."""
        if self.prefix is None:
            return None
        hit = self.prefix.lookup(ctx)
        if hit is None:
            self.metrics.record_prefix_miss()
            if self.timeline is not None:
                self.timeline.instant(
                    "prefix_miss", "serving", args={"prompt": p}
                )
            return None
        entry, m_use = hit
        if (
            self._page_size is not None and entry.page_ids is None
            and entry.host_ids
        ):
            # tiered (ISSUE 19): the matched entry is still host-resident
            # — the queue pre-pass missed it (or its device write was
            # page-starved). One LATE prefetch attempt now; the transfer
            # is still the async import dispatch, but the overlap window
            # is gone (metrics mark it late). Failure leaves page_ids
            # None and the floor-align below turns this into a miss —
            # the admission falls back to the full prefill
            self._prefetch_entry(entry, late=True)
            if entry.page_ids is None:
                self.metrics.record_prefix_miss()
                if self.timeline is not None:
                    self.timeline.instant(
                        "prefix_miss", "serving", args={"prompt": p}
                    )
                return None
        if self._page_size is not None:
            # zero-copy CoW reuse is PAGE-granular: only whole pinned pages
            # are shareable, so the usable match floor-aligns to the page
            # size (the unaligned tail re-prefills as part of the suffix)
            ps = self._page_size
            m_use = min(m_use // ps, len(entry.page_ids or ())) * ps
            if m_use < self.prefix.min_match:
                self.metrics.record_prefix_miss()
                if self.timeline is not None:
                    self.timeline.instant(
                        "prefix_miss", "serving", args={"prompt": p}
                    )
                return None
        reuse = self._prefix_reuses
        self._prefix_reuses += 1
        if self._faults is not None:
            self._faults.on_prefix_reuse(
                reuse, entry,
                cache=self.cache if self._page_size is not None else None,
            )
        if self._page_size is not None:
            # paged validation: host accounting first (the entry's pages
            # must still be allocated, pinned, and un-quarantined), then
            # CONTENT (ISSUE 20) — the used page prefix's fingerprints
            # recomputed on device against the insert-time record. An HBM
            # bit flip leaves the accounting perfectly healthy; only the
            # bit-level check catches it before the pages map into a slot
            used = entry.page_ids[:m_use // self._page_size]
            valid = self.cache.pages_live(used)
            if valid and entry.page_fp is not None and entry.hit_tier != "host":
                # a host-tier hit's bytes were CRC-verified by the store
                # at prefetch moments ago — re-validating on device would
                # re-check just-verified content and charge the prefetch
                # admission an extra sync (its budget is pinned at the
                # bare 2). The device-resident case is the one with an
                # open HBM-rot window, and it pays the one readback
                valid = self._validate_pages(entry, len(used))
        else:
            valid = self._validate_prefix(entry)
        if not valid:
            self.prefix.evict_entry(entry)
            self.metrics.record_prefix_validation_failure()
            self.metrics.record_prefix_eviction()
            self.metrics.record_prefix_miss()
            if self.timeline is not None:
                self.timeline.instant(
                    "prefix_poisoned", "serving",
                    args={"matched": m_use, "prompt": p},
                )
            return None
        self.prefix.pin(entry)
        if self._page_size is not None and entry.page_ids:
            # the hit is being consumed: the pin above protects the entry
            # from reclaim, so any in-flight prefetch hold has done its
            # job — void it (holds are claims for QUEUED work only)
            self.cache.release_prefetched(entry.page_ids)
        chunk = _suffix_bucket(p - m_use, padded, self.max_seq_len)
        tier = entry.hit_tier
        entry.hit_tier = "device"  # resident again: later hits are device
        self.metrics.record_prefix_hit(m_use, p, tier=tier)
        if self.timeline is not None:
            self.timeline.instant(
                "prefix_hit", "serving",
                args={"matched": m_use, "prompt": p, "tier": tier},
            )
        return entry, m_use, chunk

    def _validate_prefix(self, entry) -> bool:
        """Reuse-time integrity check of a stored entry: leaf shapes against
        the insert-time record, then the position-weighted fingerprint
        recomputed on device and compared with exact float equality (same
        program + same data is bit-deterministic). Cost is one scalar
        readback — the admission path syncs for the first token anyway."""
        try:
            shapes = tuple(
                tuple(leaf.shape)
                for leaf in jax.tree_util.tree_leaves(entry.tree)
            )
            if shapes != entry.shapes:
                return False
            # entry.fingerprint is a device scalar computed asynchronously
            # at insert time — long settled by now, so reading it is a
            # plain copy; the recomputation's readback is the validation
            # sync (the admission path syncs for the first token anyway).
            # Both scalars ride one explicit device_get.
            # graftlint: ok[GL02] reuse-time integrity check: one scalar
            # pair readback per prefix hit, the documented validation sync
            fp_new, fp_stored = jax.device_get(
                (self._fingerprint_fn(entry.tree), entry.fingerprint)
            )
            return float(fp_new) == float(fp_stored)
        except Exception:
            return False

    def _pages_fingerprint(self, ids):
        """Per-page content fingerprints of pool pages ``ids`` as a DEVICE
        uint32 vector — nothing syncs here (insert-time recording stays
        async, like the dense path's entry fingerprint). The id vector
        pads to the next power of two with repeats of the first id so the
        jitted program compiles once per bucket, not once per page count;
        padded positions are ignored by the caller's comparison."""
        if self._pages_fp_fn is None:
            from neuronx_distributed_tpu.utils.fingerprint import (
                pool_pages_fingerprint,
            )

            # per-engine lambda (see the _extract_fn note above)
            self._pages_fp_fn = self.programs.wrap(
                "page_fingerprint",
                jax.jit(lambda pool, pids: pool_pages_fingerprint(pool, pids)),
            )
        n = len(ids)
        bucket = 1
        while bucket < n:
            bucket <<= 1
        padded = np.asarray(
            tuple(int(i) for i in ids) + (int(ids[0]),) * (bucket - n),
            np.int32,
        )
        return self._pages_fp_fn(self.cache.cache["pool"], jnp.asarray(padded))

    def _validate_pages(self, entry, n_pages: int) -> bool:
        """Reuse-time CONTENT check of a paged entry's used page prefix:
        recompute the per-page fingerprints and compare bit-exactly with
        the insert-time record (per-page fingerprints are independent, so
        a prefix of the stored vector validates a prefix reuse). Cost is
        one small-vector readback per paged prefix hit — the same sync
        contract as the dense path's ``_validate_prefix``."""
        try:
            fp_now = self._pages_fingerprint(entry.page_ids[:n_pages])
            # graftlint: ok[GL02] reuse-time integrity check: one bucketed
            # uint32 vector readback per paged prefix hit, the documented
            # validation sync (admission syncs for the first token anyway)
            now_v, stored_v = jax.device_get((fp_now, entry.page_fp))
            return bool(np.array_equal(
                np.asarray(now_v)[:n_pages], np.asarray(stored_v)[:n_pages]
            ))
        except Exception:
            return False

    def _remember_prefix(self, ctx, p: int, padded: int, row_cache,
                         matched: int = 0) -> None:
        """Insert-on-miss (and trie extension on long partial hits):
        extract the admitted context's KV columns from the freshly built
        row into a compact COPY and store it keyed by the token path. Runs
        BEFORE ``cache.admit`` so the entry can never alias storage the
        donating slot programs will consume. Skipped for contexts too
        short to ever be reused, contexts an existing entry already
        covers, and hits whose tail extends the match by less than
        ``min_match`` (the new entry could never deliver a usefully longer
        reuse than the one that just served — and skipping keeps the hot
        hit path at three small dispatches instead of five)."""
        if self.prefix is None or p < self.prefix.min_match:
            return
        if matched and p - matched < self.prefix.min_match:
            return
        key = tuple(int(t) for t in ctx)
        if self.prefix.covers(key):
            return
        bucket = _prefix_bucket(p, self.max_seq_len)
        tree = self._extract_fn(
            row_cache,
            jnp.asarray(padded - p, jnp.int32),
            jnp.asarray(p, jnp.int32),
            bucket,
        )
        # the fingerprint stays a DEVICE scalar: forcing it to host here
        # would serialize every miss admission against the device before
        # admit/first-token even dispatch — validation (which already
        # syncs) floats it on first reuse instead
        fp = self._fingerprint_fn(tree)
        _, evicted = self.prefix.insert(key, tree, fp, bucket)
        if evicted:
            self.metrics.record_prefix_eviction(evicted)
            if self.timeline is not None:
                self.timeline.instant(
                    "prefix_evict", "serving", args={"evicted": evicted}
                )

    def _remember_prefix_paged(self, ctx, p: int, slot: int,
                               matched: int = 0) -> None:
        """Paged insert-on-miss: PIN the admitted slot's whole context
        pages instead of extracting a compact copy — zero device work, zero
        KV bytes moved, the very pages the prefill just wrote become the
        shared storage (decode writes always land beyond the aligned
        context, so a still-decoding donor can never touch them). Runs
        AFTER ``cache.admit`` (the pins ride the allocator, which donation
        cannot consume). Same skip rules as the copy path: contexts too
        short to reuse, contexts already covered, hits whose aligned tail
        adds less than ``min_match``."""
        if self.prefix is None:
            return
        ps = self._page_size
        m_ins = (p // ps) * ps
        if m_ins < self.prefix.min_match:
            return
        if matched and m_ins - matched < self.prefix.min_match:
            return
        key = tuple(int(t) for t in ctx[:m_ins])
        if self.prefix.covers(key):
            return
        ids = self.cache.slot_context_pages(slot, m_ins // ps)
        self.cache.pin_pages(ids)
        entry, evicted = self.prefix.insert(key, None, None, bucket=m_ins)
        if entry is None:  # raced covered / disabled: drop the pins
            self.cache.unpin_pages(ids)
        else:
            entry.page_ids = tuple(int(i) for i in ids)
            # integrity (ISSUE 20): record the pages' content fingerprints
            # now, while the context region is final (decode writes land
            # beyond the aligned context by construction). Stays a device
            # vector — no sync on the miss-admission path; first reuse
            # floats it alongside the recomputation
            entry.page_fp = self._pages_fingerprint(entry.page_ids)
        if evicted:
            self.metrics.record_prefix_eviction(evicted)
            if self.timeline is not None:
                self.timeline.instant(
                    "prefix_evict", "serving", args={"evicted": evicted}
                )

    # --- decode -------------------------------------------------------------

    def _maybe_profile(self) -> None:
        """``profile_dir`` knob: capture a ``jax.profiler`` device trace of
        decode chunks [2, 5) — past the compile/warmup chunks, bounded so
        an unattended server never accumulates an unbounded trace (the
        trainer's ``profile_dir`` profiles steps [2, 5) the same way)."""
        if self._profile_dir is None:
            return
        chunks = self.metrics.chunks  # successful chunks so far
        if not self._profiling and chunks == 2:
            try:
                jax.profiler.start_trace(self._profile_dir)
            except Exception as e:
                # a profiler that cannot start (another trace already
                # active, unwritable dir) must cost the serving loop its
                # profile, never its requests — disable and move on
                self._profile_dir = None
                if self.flight is not None:
                    self.flight.record("profile_start_failed", error=str(e))
                return
            self._profiling = True
        elif self._profiling and chunks >= 5:
            self._stop_profile()

    def _stop_profile(self) -> None:
        self._profiling = False
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass  # a failed stop must never take the serving loop down

    def _decode(self) -> None:
        """One fused decode chunk: dispatch the donated jitted scan, then a
        SINGLE host synchronization for the whole token block. Between here
        and the next admission/free event no per-slot host state moves. A
        failed dispatch routes through the recovery state machine instead of
        crashing the loop."""
        if self._page_size is not None and not self._ensure_decode_pages():
            # page-pressure wall: the pool cannot back every active slot's
            # next write window even after reclaiming prefix entries —
            # preempt-and-rewind, the cursor wall's exact remedy (frees
            # every slot mapping; re-admission repacks from column 0)
            self._preempt_all()
            return
        if self.draft_model is not None:
            self._decode_spec()
        else:
            self._decode_plain()

    def _nonspec_chunk(self):
        """The plain fused chunk — built lazily on a speculative engine
        (only a failed speculative dispatch ever needs it)."""
        if self._decode_chunk is None:
            self._decode_chunk = jax.jit(
                chunked_decode_step(
                    self._decode_model, self.decode_chunk_size,
                    self.max_seq_len, page_size=self._page_size,
                ),
                donate_argnums=(1, 2),
            )
            self._decode_chunk = self.programs.wrap(
                "decode_chunk", self._comms_scoped(self._decode_chunk)
            )
        return self._decode_chunk

    def _decode_spec(self) -> None:
        """One fused SPECULATIVE chunk: ``decode_chunk_size`` draft–verify
        rounds through both donated caches, one host sync for the ragged
        per-slot token block. A failed dispatch falls back to a plain
        non-speculative chunk for THIS chunk when the donated buffers
        survived (streams bit-identical — the fallback is the very program
        the spec-off engine runs), then preempts to resync the draft cache;
        consumed buffers route through full dispatch recovery."""
        tl = self.timeline
        active_at_dispatch = int(self._active.sum())
        self._maybe_profile()
        if tl is not None:
            tl.mark_event_start("decode_dispatch", "serving")
        t0 = self._clock()
        cache_in = self.cache.take()
        draft_in = self.draft_cache.take()
        attempt = self._dispatch_attempts
        self._dispatch_attempts += 1
        dparams = self._draft_params
        try:
            if self._faults is not None:
                self._faults.on_dispatch(attempt)
                self._faults.on_spec_dispatch(attempt)
                dparams = self._faults.on_spec_params(attempt, dparams)
            (new_cache, new_draft, self._state, toks, counts, accepts,
             used, key_snap) = self._spec_chunk(
                self._params, dparams, cache_in, draft_in, self._state
            )
        except BaseException as e:
            if tl is not None:
                tl.mark_event_end("decode_dispatch", "serving")
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit are the operator's, not
                # faults: restore both references and re-raise
                self.cache.restore(cache_in)
                self.draft_cache.restore(draft_in)
                raise
            self._spec_fallback(cache_in, draft_in, e)
            return
        t1 = self._clock()
        self._consecutive_dispatch_failures = 0
        self._chunks_since_failure += 1
        if tl is not None:
            tl.mark_event_end("decode_dispatch", "serving")
            tl.mark_event_start("decode_readback", "serving")
        # THE one host sync per speculative chunk: the ragged (rounds,
        # slots, gamma) token block, per-round per-slot counts + accepted
        # draft lengths, the executed round count, and the post-chunk key
        # snapshot — whatever the per-slot acceptance pattern emitted
        # graftlint: ok[GL02] THE one per-chunk sync of the fused
        # speculative decode contract (pinned in test_host_sync.py)
        toks, counts, accepts, used, chunk_keys = jax.device_get(
            (toks, counts, accepts, used, key_snap)
        )
        t2 = self._clock()
        readback = self._readbacks
        self._readbacks += 1
        if self._faults is not None:
            toks, counts = self._faults.on_spec_readback(
                readback, toks, counts, self._active
            )
        # executed ROUNDS drive cursor arithmetic (gamma columns per round
        # in BOTH caches); clamp so corrupted output can never run away
        used = max(0, min(int(used), self.decode_chunk_size))
        self.cache.update_after_decode(new_cache, used * self.gamma)
        self.draft_cache.update_after_decode(new_draft, used * self.gamma)
        bad = _validate_spec_readback(
            toks, counts, self.gamma, self._vocab,
            np.flatnonzero(self._active),
        )
        # paged: page-poison victims leave self._active before the unpack
        self._apply_page_poison(readback)
        emitted = int(
            sum(
                int(counts[:, s].sum())
                for s in np.flatnonzero(self._active)
                if int(s) not in bad
            )
        )
        if tl is not None:
            tl.mark_event_end(
                "decode_readback", "serving",
                args={"tokens": emitted, "rounds": used},
            )
        now = self._now()
        delivered = 0
        spec_accepts = []
        for slot in np.flatnonzero(self._active):
            req = self._slot_req[slot]
            if int(slot) in bad:
                self._quarantine_slot(int(slot), req, bad[int(slot)], now)
                continue
            req.key = np.array(chunk_keys[slot], np.uint32)
            # acceptance stats at full per-slot-per-round resolution: a
            # live round is one where the slot emitted (live slots emit
            # >= 1 per round) — all host scalars off the single sync
            live_rounds = [
                r for r in range(counts.shape[0]) if int(counts[r, slot]) > 0
            ]
            spec_accepts.extend(int(accepts[r, slot]) for r in live_rounds)
            self.tracer.step(
                req.rid, "decode_chunk",
                args={
                    "tokens": int(counts[:, slot].sum()),
                    "rounds": used,
                    "accepted": int(accepts[:, slot].sum()),
                },
            )
            for r in live_rounds:
                for tok in toks[r, slot, : int(counts[r, slot])]:
                    self._emit_token(req, int(tok), now)
                    delivered += 1
                    self._maybe_finish(req, now)
                    if req.finished:
                        break
                if req.finished:
                    break
        if tl is not None:
            tl.counter("chunk_tokens", delivered, "serving")
            tl.instant(
                "spec_accept", "serving",
                args={
                    "accepted": int(sum(spec_accepts)),
                    "drafted": self.gamma * len(spec_accepts),
                    "rounds": used,
                    "tokens": delivered,
                },
            )
        self.metrics.record_decode_chunk(
            delivered, used, self.cache.cursor, active_at_dispatch,
            dispatch_s=t1 - t0, readback_s=t2 - t1,
            spec_accepts=spec_accepts, gamma=self.gamma,
        )
        # roofline feed: the chunk's measured wall (already host floats off
        # the single readback) against the ledgered program cost — a
        # compile-polluted first chunk is skipped so MFU never averages in
        # trace+compile time
        if not self._spec_chunk.last_call_compiled:
            self.programs.observe_wall("spec_decode_chunk", t2 - t0)

    def _spec_fallback(self, cache_in, draft_in, exc: Exception) -> None:
        """A SPECULATIVE dispatch failed. When the donated buffers
        survived (host-side failure — injected draft fault, enqueue
        error), decode THIS chunk with the plain non-speculative program —
        the exact program a spec-off engine runs, so streams continue
        bit-identically and no token is lost — then preempt-and-resync:
        the fallback chunk advanced the target cache without the draft
        cache, and re-prefilling both on re-admission restores lockstep
        (and full acceptance) at the cost of one re-prefill per slot.
        Consumed buffers mean the failure happened inside XLA — nothing to
        fall back ONTO — so it routes through full dispatch recovery."""
        consumed = any(
            getattr(leaf, "is_deleted", lambda: False)()
            for tree in (cache_in, draft_in, self._state)
            for leaf in jax.tree_util.tree_leaves(tree)
        )
        if consumed:
            # nothing to fall back ONTO — this is a full dispatch failure,
            # counted/recorded as one by the recovery path (spec_fallbacks
            # counts only chunks actually decoded non-speculatively)
            self._recover_dispatch(cache_in, exc, draft_in=draft_in)
            return
        self.cache.restore(cache_in)
        self.draft_cache.restore(draft_in)
        # mark the failure window (DEGRADED until the cooldown elapses);
        # NOT a consecutive-failure count — the fallback below makes
        # progress, and ITS dispatch failure is what escalates to recovery
        # and, bounded, to HALT
        self._had_dispatch_failure = True
        self._chunks_since_failure = 0
        self._decode_plain()
        if self._consecutive_dispatch_failures == 0:
            # the plain chunk really decoded (its own failure would have
            # routed through recovery and bumped the consecutive count):
            # THIS is a fallback — the counter means "chunk decoded
            # non-speculatively", never "speculative dispatch failed"
            self.metrics.record_spec_fallback()
            if self.timeline is not None:
                self.timeline.instant(
                    "spec_fallback", "serving", args={"error": str(exc)[:200]}
                )
            if self.flight is not None:
                self.flight.record("spec_fallback", error=str(exc))
        if any(self._active):
            # the fallback chunk advanced only the target cache: preempt
            # so re-admission rebuilds BOTH caches in lockstep (tokens and
            # keys are host-current — the preemption contract)
            self._preempt_all()
        self._sync_health()

    def _decode_plain(self) -> None:
        """The non-speculative fused chunk (the pre-ISSUE-9 `_decode` body;
        also the speculative engine's fallback program)."""
        tl = self.timeline
        active_at_dispatch = int(self._active.sum())
        self._maybe_profile()
        if tl is not None:
            tl.mark_event_start("decode_dispatch", "serving")
        t0 = self._clock()
        cache_in = self.cache.take()
        attempt = self._dispatch_attempts
        self._dispatch_attempts += 1
        try:
            if self._faults is not None:
                self._faults.on_dispatch(attempt)
            (new_cache, self._state, toks, counts, used,
             key_snap) = self._nonspec_chunk()(
                self._params, cache_in, self._state
            )
        except BaseException as e:
            if tl is not None:
                tl.mark_event_end("decode_dispatch", "serving")
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit are the operator's, not
                # faults: restore the reference (a consumed buffer fails
                # loudly on next use) and re-raise
                self.cache.restore(cache_in)
                raise
            self._recover_dispatch(cache_in, e)
            return
        t1 = self._clock()
        self._consecutive_dispatch_failures = 0
        self._chunks_since_failure += 1
        if tl is not None:
            tl.mark_event_end("decode_dispatch", "serving")
            tl.mark_event_start("decode_readback", "serving")
        # THE one host sync per chunk: the (chunk, slots) token block, the
        # per-slot valid-prefix lengths, the executed step count — and the
        # post-chunk key SNAPSHOT (frozen at each slot's finish step). The
        # snapshot is a chunk OUTPUT, not the state leaf: device_get on the
        # leaf would cache a host value on it and silently demote the next
        # chunk's keys donation to a copy
        # graftlint: ok[GL02] THE one per-chunk sync of the fused decode
        # contract (tests/serving/test_decode_chunking.py pins it at 1)
        toks, counts, used, chunk_keys = jax.device_get(
            (toks, counts, used, key_snap)
        )
        t2 = self._clock()
        readback = self._readbacks
        self._readbacks += 1
        if self._faults is not None:
            toks, counts = self._faults.on_readback(
                readback, toks, counts, self._active
            )
        # the executed step count drives cursor arithmetic — clamp it to the
        # chunk bound so corrupted output can never run the cursor away
        used = max(0, min(int(used), self.decode_chunk_size))
        self.cache.update_after_decode(new_cache, used)
        # validate the block BEFORE any token reaches a stream: a poisoned
        # slot is quarantined and its chunk discarded; neighbors proceed
        bad = _validate_readback(
            toks, counts, self.decode_chunk_size, self._vocab,
            np.flatnonzero(self._active),
        )
        # paged engines: a poisoned PAGE quarantines only the requests
        # mapping it — victims leave self._active here, so the emit loop
        # below never touches their (discarded) readback columns
        self._apply_page_poison(readback)
        emitted = int(
            sum(
                int(counts[s])
                for s in np.flatnonzero(self._active)
                if int(s) not in bad
            )
        )
        if tl is not None:
            tl.mark_event_end(
                "decode_readback", "serving",
                args={"tokens": emitted, "steps": used},
            )
        now = self._now()
        delivered = 0
        for slot in np.flatnonzero(self._active):
            req = self._slot_req[slot]
            if int(slot) in bad:
                self._quarantine_slot(int(slot), req, bad[int(slot)], now)
                continue
            # mirror the post-chunk key onto the host request: preemption,
            # dispatch recovery, and retirement all read req.key — keeping
            # it current at every chunk boundary costs nothing (the snapshot
            # already rode the chunk's single sync)
            req.key = np.array(chunk_keys[slot], np.uint32)
            # per-request flow waypoint: the chunk's already-host token
            # count — links this chunk into the request's trace for free
            self.tracer.step(
                req.rid, "decode_chunk",
                args={"tokens": int(counts[slot]), "steps": used},
            )
            for tok in toks[: int(counts[slot]), slot]:
                self._emit_token(req, int(tok), now)
                delivered += 1
                self._maybe_finish(req, now)
                if req.finished:
                    # EOS/budget retired it, or an on_token callback
                    # cancelled it: discard the rest of its block
                    break
        # recorded after the unpack so a mid-chunk cancellation's discarded
        # device tokens never inflate decode_tokens / chunk tok/s
        if tl is not None:
            tl.counter("chunk_tokens", delivered, "serving")
        self.metrics.record_decode_chunk(
            delivered, used, self.cache.cursor, active_at_dispatch,
            dispatch_s=t1 - t0, readback_s=t2 - t1,
        )
        # roofline feed (see _decode_spec): measured chunk wall, compile
        # chunks excluded
        if not self._decode_chunk.last_call_compiled:
            self.programs.observe_wall("decode_chunk", t2 - t0)

    def _recover_dispatch(self, cache_in, exc: Exception,
                          draft_in=None) -> None:
        """A decode dispatch FAILED. Recovery = the preemption machinery:
        every in-flight request goes back to the queue front with its
        host-current tokens and key (both exact as of the last chunk
        boundary), the cache storage is salvaged when the donated buffers
        survived (or dropped for lazy reallocation when XLA consumed them),
        and the next step re-prefills and retries. After
        ``dispatch_retry.max_attempts`` CONSECUTIVE failures the engine
        HALTS with the work requeued instead of crashing."""
        n = self._consecutive_dispatch_failures + 1
        self._consecutive_dispatch_failures = n
        self._had_dispatch_failure = True
        self._chunks_since_failure = 0
        self.metrics.record_dispatch_retry()
        if self.timeline is not None:
            self.timeline.instant(
                "dispatch_failure", "serving",
                args={"error": str(exc)[:200], "consecutive": n},
            )
        if self.flight is not None:
            self.flight.record("dispatch_failure", error=str(exc),
                               consecutive=n)
        requeued = self._vacate_active()
        for r in requeued:
            self.tracer.step(r.rid, "recovery_requeue",
                             args={"tokens": len(r.tokens)})
        self.scheduler.requeue_front(requeued)
        self.cache.release_all_slots()
        survived = self.cache.recover(cache_in)
        if self._page_size is not None and not survived and (
            self.prefix is not None
        ):
            # the POOL was consumed: every pinned page's content is gone
            # with it, so paged entries (which hold no copies) are void —
            # clear the store; on_evict releases each entry's page refs
            dropped = self.prefix.clear()
            if dropped:
                self.metrics.record_prefix_eviction(dropped)
        if self.draft_cache is not None:
            # the draft twin recovers identically: salvage-or-drop its
            # storage and rewind — every slot was vacated, so a lazy
            # reallocation on the next admission is safe for both
            self.draft_cache.release_all_slots()
            self.draft_cache.recover(
                draft_in if draft_in is not None else self.draft_cache.take()
            )
        self._state = self._fresh_slot_state()
        if self.prefix is not None:
            # recovery never resurrects stale KV THROUGH the prefix store:
            # entries are independent copies (still valid under the same
            # weights), but any pin a failed admission might have left is
            # dropped so eviction can proceed
            self.prefix.release_all()
        if n >= self._dispatch_retry.max_attempts:
            self._halt(
                f"{n} consecutive dispatch failures (last: "
                f"{type(exc).__name__}: {exc})"
            )
            return
        self.metrics.record_recovery(len(requeued))
        if self.timeline is not None:
            self.timeline.instant(
                "recovery", "serving", args={"requeued": len(requeued)}
            )
        if self.flight is not None:
            self.flight.record("recovery", requeued=len(requeued),
                               consecutive=n)
        # shared decrementing-jitter wait before the next attempt (attempt
        # index is 0-based): ride out a transient burst without hammering
        self._sleep(self._dispatch_retry.wait(n - 1))
        self._sync_health()

    def _quarantine_slot(self, slot: int, req: Optional[Request],
                         reason: str, now: float) -> None:
        """Pull a poisoned slot out of the rotation before its chunk
        reaches a stream. The victim request resumes from the last chunk
        boundary in a DIFFERENT slot (``quarantine_policy="requeue"``,
        bit-identical — its tokens and key were untouched by the poisoned
        chunk) or fails for cause (``"fail"``). Neighbors are unaffected;
        losing the last usable slot halts the engine."""
        self.metrics.record_quarantine(slot, req.rid if req else None)
        if self.timeline is not None:
            self.timeline.instant(
                f"quarantine slot {slot}", "serving",
                args={"reason": reason, "rid": req.rid if req else None},
            )
        if self.flight is not None:
            self.flight.record("quarantine", slot=slot,
                               rid=req.rid if req else None, reason=reason,
                               tenant=req.tenant if req else None)
        self._slot_req[slot] = None
        self._active[slot] = False
        self._state = self._slot_clear(self._state, np.int32(slot))
        self.cache.quarantine(slot)
        self.cache.free(slot)  # clears the row; never rejoins the rotation
        if self.draft_cache is not None:
            # the draft row is equally suspect — quarantine it in lockstep
            self.draft_cache.quarantine(slot)
            self.draft_cache.free(slot)
        if req is not None:
            req.slot = None
            if self._quarantine_policy == "requeue" and not req.finished:
                self.tracer.step(req.rid, "quarantine_requeue",
                                 args={"slot": slot})
                self.scheduler.requeue_front([req])
            else:
                req.state = RequestState.FAILED
                req.error = f"slot {slot} quarantined: {reason}"
                req.finish_time = now
                self.metrics.record_failed(req, now, kind="quarantine")
                self.tracer.end(req.rid, "failed",
                                args={"kind": "quarantine", "slot": slot})
                self._on_token.pop(req.rid, None)
        if self.cache.usable_slots == 0:
            self._halt("all slots quarantined")
        elif (
            self._page_size is not None and self.cache.alloc.capacity == 0
        ):
            # paged: the slot index survives a quarantine (only its
            # exclusive PAGES retire), so total capacity loss shows up in
            # the pool, not the slot count
            self._halt("all KV pages quarantined")

    # --- lifecycle helpers --------------------------------------------------

    def _emit_token(self, req: Request, tok: int, now: float,
                    first: bool = False) -> None:
        req.tokens.append(tok)
        # fairness accounting (ISSUE 16): charge the tenant's decode-token
        # budget — host ints the loop already owns, FIFO's hook is a no-op
        self.policy.on_tokens(req.tenant, 1)
        if first:
            req.first_token_time = now
            self.metrics.record_first_token(req, now)
        cb = self._on_token.get(req.rid)
        if cb is not None:
            cb(req, tok)

    def _maybe_finish(self, req: Request, now: float) -> None:
        if req.state is RequestState.CANCELLED:
            # e.g. an on_token callback cancelled it this very step: the
            # cancellation wins; _reap_cancelled retires the slot next step
            return
        eos = req.config.eos_token_id
        hit_eos = eos is not None and req.tokens and req.tokens[-1] == eos
        if hit_eos or len(req.tokens) >= req.config.max_new_tokens:
            req.state = RequestState.DONE
            req.finish_time = now
            self.metrics.record_finish(req, now)
            self._release_slot(req)
            if self.timeline is not None:
                self.timeline.instant(f"done r{req.rid}", "serving")
            self.tracer.end(req.rid, "retire",
                            args={"tokens": len(req.tokens),
                                  "tenant": req.tenant})

    def _release_slot(self, req: Request) -> None:
        slot = req.slot
        if slot is None:
            return
        req.slot = None
        self._slot_req[slot] = None
        self._active[slot] = False
        self._state = self._slot_clear(self._state, np.int32(slot))
        self.cache.free(slot)
        if self.draft_cache is not None:
            self.draft_cache.free(slot)
        self._on_token.pop(req.rid, None)

    def _reap_cancelled(self, now: float) -> None:
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.state is RequestState.CANCELLED:
                self.metrics.record_cancel(req, now)
                req.finish_time = now
                self.tracer.end(req.rid, "cancelled",
                                args={"where": "slot", "slot": slot})
                self._release_slot(req)

    def _vacate_active(self) -> List[Request]:
        """Unbind every active request from its slot (host bookkeeping
        only) and return them in slot order — the shared first half of
        preemption and dispatch recovery. ``req.key``/``req.tokens`` are
        already host-current as of the last chunk boundary, so no device
        state is touched (it may not even exist after a failed donation)."""
        vacated = [r for r in self._slot_req if r is not None]
        for req in vacated:
            slot, req.slot = req.slot, None
            self._slot_req[slot] = None
            self._active[slot] = False
        return vacated

    def _preempt_victims(self, victims: List[Request], now: float) -> None:
        """Policy-chosen SELECTIVE preemption (ISSUE 16): vacate just the
        nominated slots — host bookkeeping + the per-slot clear/free the
        retirement path already uses — and requeue the victims with their
        host-current tokens and keys. Resume re-prefills ``context_ids``
        and continues at ``req.key``, the same contract as preempt-all and
        quarantine-requeue, so the victim's stream is bit-identical. The
        shared cursor is NOT rewound (other slots keep decoding); the
        paged layout gets the victim's exclusive pages back immediately."""
        for req in victims:
            slot = req.slot
            if slot is None or req.finished:
                continue  # retired or shed since nomination — nothing held
            req.slot = None
            self._slot_req[slot] = None
            self._active[slot] = False
            self._state = self._slot_clear(self._state, np.int32(slot))
            self.cache.free(slot)
            if self.draft_cache is not None:
                self.draft_cache.free(slot)
            req.preemptions += 1
            self.metrics.record_preemption(req)
            self.tracer.step(
                req.rid, "slo_preempt",
                args={"slot": slot, "tokens": len(req.tokens),
                      "tenant": req.tenant},
            )
            if self.timeline is not None:
                self.timeline.instant(
                    f"slo_preempt r{req.rid}", "serving",
                    args={"slot": slot, "tenant": req.tenant},
                )
            if self.flight is not None:
                self.flight.record("slo_preempt", rid=req.rid, slot=slot,
                                   tenant=req.tenant,
                                   tokens=len(req.tokens))
            self.scheduler.requeue_front([req])

    def _preempt_all(self) -> None:
        """Out of cache columns: push every active request back to the queue
        (keeping its generated tokens and its host-mirrored key), rewind the
        cache, and let admission re-prefill their contexts. Token streams
        are unaffected — resume replays the exact context the request had."""
        preempted = self._vacate_active()
        for req in preempted:
            req.preemptions += 1
            self.metrics.record_preemption(req)
            self.tracer.step(req.rid, "preempt",
                             args={"tokens": len(req.tokens)})
        self.scheduler.requeue_front(preempted)
        # ONE device reset invalidates every row — per-slot free() dispatches
        # here would be N redundant full-cache programs; only the host
        # free-list needs per-slot bookkeeping
        self.cache.release_all_slots()
        self.cache.reset()
        if self.draft_cache is not None:
            self.draft_cache.release_all_slots()
            self.draft_cache.reset()
        # every slot is empty now; re-admission re-uploads each row, so a
        # fresh zero state is cheaper than N per-slot clears
        self._state = self._fresh_slot_state()
        if self.timeline is not None:
            self.timeline.instant(
                f"preempt x{len(preempted)}", "serving"
            )

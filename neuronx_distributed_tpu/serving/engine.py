"""Continuous-batching serving engine.

``ServingEngine`` turns the repo's batch ``generate()`` math into a
request-level server: a host-side loop interleaves prefill of admitted
requests with ONE jitted fixed-shape decode step over all ``num_slots``
slots. The decode step's shapes never change — cache ``(num_slots,
max_seq_len)``, per-slot token/key/config arrays — so XLA compiles it exactly
once and every request, whatever its arrival time, length, or sampling
config, flows through the same program (``decode_compilations`` asserts
this). Prefill compiles once per padded-length bucket (powers of two), the
standard serving trade.

Token-stream fidelity: a request served through the engine produces EXACTLY
the tokens of a solo ``generate(prompt, key)`` call — same prefill math
(left-padded prompts are already proven token-identical to unpadded ones),
same per-step key evolution (``split`` then sample with the sub-key), and a
per-row sampler that is bit-identical to ``sample`` (utils/sampling.py). The
engine is a scheduler around the same program, not a different generator.

Cache capacity: all slots share one write cursor (see
``serving/cache_manager.py``), which advances every decode step while ANY
slot is active. Admission guards against running past ``max_seq_len``:

* ``admission="conservative"`` (default) — admit only when the request's
  whole remaining generation fits under the cursor; requests queue
  otherwise, and the cursor rewinds whenever the engine drains.
* ``admission="eager"`` — admit whenever the prefill itself fits; when the
  cursor hits the wall the engine preempts every active request (their
  progress is kept), rewinds the cache, and resumes them by re-prefilling
  their context — trading re-prefill compute for slot utilization.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_tpu.inference.generate import (
    GenerationConfig,
    serving_clones,
)
from neuronx_distributed_tpu.inference.utils import unwrap_logits
from neuronx_distributed_tpu.serving.cache_manager import SlotCacheManager
from neuronx_distributed_tpu.serving.metrics import ServingMetrics
from neuronx_distributed_tpu.serving.scheduler import (
    Request,
    RequestState,
    Scheduler,
)
from neuronx_distributed_tpu.utils.sampling import sample_per_row, sample_row


def _key_data(key) -> np.ndarray:
    """Raw (2,) uint32 view of a PRNG key (typed or legacy)."""
    dt = getattr(key, "dtype", None)
    if dt is not None and jnp.issubdtype(dt, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    return np.asarray(key, np.uint32)


def _config_sentinels(cfg: GenerationConfig):
    """(temperature, top_k, top_p) with the traced-sampler sentinels:
    top_k<=0 and top_p>=1 disable the filters (sample_row's contract)."""
    return (
        np.float32(cfg.temperature),
        np.int32(cfg.top_k if cfg.top_k is not None else 0),
        np.float32(cfg.top_p if cfg.top_p is not None else 1.0),
    )


def _bucket(p: int, max_seq_len: int, remaining: int, floor: int = 8) -> int:
    """Padded prefill length for a p-token context: next power of two
    (compile-count control), clamped so the padded prompt still leaves room
    for the remaining generation — falling back to the exact length keeps
    every feasible request admittable at the cost of a per-length compile."""
    b = max(floor, 1 << max(p - 1, 0).bit_length())
    b = min(b, max_seq_len)
    if b < p or b + remaining > max_seq_len:
        b = p
    return b


class ServingEngine:
    """Slot-based continuous batching over a mode-capable causal LM."""

    def __init__(
        self,
        model,
        params,
        num_slots: int,
        max_tokens_in_flight: Optional[int] = None,
        admission: str = "conservative",
        timeline=None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        if admission not in ("conservative", "eager"):
            raise ValueError(f"unknown admission policy {admission!r}")
        max_seq_len = getattr(getattr(model, "config", None), "max_seq_len", None)
        if max_seq_len is None:
            raise ValueError(
                "ServingEngine needs model.config.max_seq_len (the fixed "
                "slot cache length)"
            )
        self.model = model
        self.params = params
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.admission = admission
        self.timeline = timeline
        self._clock = time_fn
        self._prefill_model, self._decode_model = serving_clones(model)
        self.scheduler = Scheduler(max_tokens_in_flight)
        self.cache = SlotCacheManager(num_slots)
        self.metrics = ServingMetrics(num_slots)
        # per-slot device-step state (host numpy mirrors; fixed shapes)
        self._tok = np.zeros((num_slots,), np.int32)
        self._keys = np.zeros((num_slots, 2), np.uint32)
        self._active = np.zeros((num_slots,), bool)
        self._temp = np.ones((num_slots,), np.float32)
        self._topk = np.zeros((num_slots,), np.int32)
        self._topp = np.ones((num_slots,), np.float32)
        self._slot_req: List[Optional[Request]] = [None] * num_slots
        self._on_token: Dict[int, Callable[[Request, int], None]] = {}
        self._next_rid = 0
        self._prefill_fns: Dict[int, Callable] = {}
        self._decode_step = jax.jit(self._decode_step_impl)
        self._first_token = jax.jit(sample_row)

    # --- public API ---------------------------------------------------------

    def submit(
        self,
        prompt_ids,
        config: GenerationConfig = GenerationConfig(),
        key=None,
        on_token: Optional[Callable[[Request, int], None]] = None,
    ) -> Request:
        """Enqueue one request; returns its live ``Request`` (``tokens``
        fills in as the engine steps). ``key`` defaults to a per-request
        PRNGKey; pass the key you would give ``generate`` to reproduce its
        stream exactly."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if config.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + config.max_new_tokens > self.max_seq_len:
            # same contract as generate(): past max_seq_len the cache write
            # index and RoPE positions would clamp and corrupt output
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({config.max_new_tokens}) exceeds max_seq_len "
                f"({self.max_seq_len})"
            )
        budget = self.scheduler.max_tokens_in_flight
        if budget is not None and prompt.size + config.max_new_tokens > budget:
            # a footprint over the whole budget can NEVER be admitted —
            # queueing it would livelock run() behind a permanent FIFO head
            raise ValueError(
                f"request footprint ({prompt.size + config.max_new_tokens}) "
                f"exceeds max_tokens_in_flight ({budget}); it could never "
                "be admitted"
            )
        rid = self._next_rid
        self._next_rid += 1
        if key is None:
            key = jax.random.PRNGKey(rid)
        req = Request(
            rid=rid, prompt=prompt, config=config, key=_key_data(key)
        )
        req.submit_time = self._clock()
        if on_token is not None:
            self._on_token[rid] = on_token
        self.scheduler.submit(req)
        self.metrics.record_submit(req, req.submit_time)
        if self.timeline is not None:
            self.timeline.instant(f"submit r{rid}", "serving")
        return req

    def cancel(self, rid: int) -> bool:
        """Cancel a request. Queued: dropped immediately; running: its slot
        is reaped at the next step."""
        req = self.scheduler.get(rid)
        if req is None or req.finished:
            return False
        was_queued = req.slot is None
        ok = self.scheduler.cancel(rid)
        if ok and was_queued:
            self.metrics.record_cancel(req, self._clock())
        return ok

    @property
    def has_work(self) -> bool:
        return self.scheduler.queued > 0 or any(self._active)

    @property
    def decode_compilations(self) -> int:
        """How many distinct decode-step programs XLA compiled. Stays 1
        across arbitrary slot churn — the continuous-batching invariant."""
        return int(self._decode_step._cache_size())

    def step(self) -> bool:
        """One engine iteration: reap cancellations → preempt/rewind if the
        cursor is out of room → admit+prefill → one decode step → retire
        finished slots. Returns whether work remains."""
        now = self._clock()
        self._reap_cancelled(now)
        if any(self._active) and self.cache.cursor >= self.max_seq_len:
            self._preempt_all()
        if not any(self._active) and self.cache.cursor > 0:
            # drained: rewind the shared cursor so the next wave starts at
            # column 0 (storage reused, nothing reallocated)
            self.cache.reset()
        self._admit(now)
        if any(self._active):
            self._decode(now)
        if self.timeline is not None:
            self.timeline.counter("slots_active", int(self._active.sum()), "serving")
            self.timeline.counter("queue_depth", self.scheduler.queued, "serving")
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        """Step until idle; returns every request this engine has seen."""
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return {r.rid: r for r in self.scheduler.requests.values()}

    # --- admission ----------------------------------------------------------

    def _in_flight_tokens(self) -> int:
        return sum(
            r.token_footprint for r in self._slot_req if r is not None
        )

    def _admit(self, now: float) -> None:
        if self.cache.free_slots == 0 or self.scheduler.queued == 0:
            return
        proj = self.cache.cursor
        maxrem = max(
            (r.remaining_new_tokens for r in self._slot_req if r is not None),
            default=0,
        )

        def fits(req: Request) -> bool:
            nonlocal proj, maxrem
            p = len(req.context_ids)
            bucket = _bucket(p, self.max_seq_len, req.remaining_new_tokens)
            target = max(proj, bucket)
            if self.admission == "conservative":
                # all slots step together, so the cursor's final resting
                # place is the admission cursor plus the LONGEST remaining
                # generation in flight — a long prompt's cursor jump must
                # not strand the slots already running (they'd hit the
                # preemption wall conservative mode promises to avoid)
                if (
                    target + max(maxrem, req.remaining_new_tokens)
                    > self.max_seq_len
                ):
                    return False
            elif target + 1 > self.max_seq_len:
                # eager: just the prefill + one decode step must fit; the
                # preemption path recovers the rest
                return False
            proj = target
            maxrem = max(maxrem, req.remaining_new_tokens)
            return True

        selected = self.scheduler.select(
            self.cache.free_slots, self._in_flight_tokens(), fits
        )
        for req in selected:  # longest-prefill-first
            self._prefill_into_slot(req, self.cache.acquire(), now)

    def _prefill_fn(self, padded_len: int):
        fn = self._prefill_fns.get(padded_len)
        if fn is None:
            prefill = self._prefill_model

            @jax.jit
            def fn(params, ids, mask):
                out, variables = prefill.apply(
                    params, ids, padding_mask=mask, mutable=["cache"]
                )
                return unwrap_logits(out)[0, -1], variables["cache"]

            self._prefill_fns[padded_len] = fn
        return fn

    def _prefill_into_slot(self, req: Request, slot: int, now: float) -> None:
        ctx = req.context_ids
        p = len(ctx)
        padded = _bucket(p, self.max_seq_len, req.remaining_new_tokens)
        ids = np.zeros((1, padded), np.int32)
        mask = np.zeros((1, padded), bool)
        ids[0, padded - p:] = ctx  # LEFT padding: last token at index -1
        mask[0, padded - p:] = True
        if self.timeline is not None:
            self.timeline.mark_event_start("prefill", "serving")
        logits, row_cache = self._prefill_fn(padded)(
            self.params, jnp.asarray(ids), jnp.asarray(mask)
        )
        if self.timeline is not None:
            self.timeline.mark_event_end("prefill", "serving")
        self.cache.admit(row_cache, slot, padded)
        self.metrics.record_admit(req, now)
        if req.admit_time is None:
            req.admit_time = now
        if not req.tokens:
            # fresh request: sample the first token exactly as generate()
            # does — split the request key, sample with the sub-key
            carry, sub = jax.random.split(jnp.asarray(req.key))
            temp, topk, topp = _config_sentinels(req.config)
            tok0 = int(self._first_token(logits, sub, temp, topk, topp))
            req.key = np.asarray(carry, np.uint32)
            self._emit_token(req, tok0, now, first=True)
        req.state = RequestState.DECODE
        req.slot = slot
        self._slot_req[slot] = req
        self._tok[slot] = req.tokens[-1]
        self._keys[slot] = req.key
        self._temp[slot], self._topk[slot], self._topp[slot] = (
            _config_sentinels(req.config)
        )
        self._active[slot] = True
        # a request can be born finished (max_new_tokens == 1, or EOS as
        # its very first token) — retire before it ever decodes
        self._maybe_finish(req, now)

    # --- decode -------------------------------------------------------------

    def _decode_step_impl(self, params, cache, tok, keys, active,
                          temp, topk, topp):
        """THE fixed-shape decode step: one token for every slot, per-slot
        sampling config, per-slot key split. Inactive slots still compute
        (fixed shapes are the point) but their K/V writes are masked
        invalid so freed slots never pollute attendable context."""
        split = jax.vmap(jax.random.split)(keys)  # (B, 2, 2)
        carry_keys, subs = split[:, 0], split[:, 1]
        out, variables = self._decode_model.apply(
            {**params, "cache": cache}, tok[:, None],
            padding_mask=active[:, None], mutable=["cache"],
        )
        logits = unwrap_logits(out)[:, -1]
        nxt = sample_per_row(logits, subs, temp, topk, topp)
        return variables["cache"], carry_keys, nxt

    def _decode(self, now: float) -> None:
        if self.timeline is not None:
            self.timeline.mark_event_start("decode_step", "serving")
        new_cache, new_keys, nxt = self._decode_step(
            dict(self.params), self.cache.cache,
            jnp.asarray(self._tok), jnp.asarray(self._keys),
            jnp.asarray(self._active), jnp.asarray(self._temp),
            jnp.asarray(self._topk), jnp.asarray(self._topp),
        )
        self.cache.update_after_decode(new_cache)
        self.metrics.record_decode_step(
            int(self._active.sum()), self.cache.cursor
        )
        nxt = np.asarray(nxt)
        # np.array (not asarray): device arrays view as read-only, but the
        # admission path writes per-slot keys into this mirror
        self._keys = np.array(new_keys)
        if self.timeline is not None:
            self.timeline.mark_event_end("decode_step", "serving")
        for slot in np.flatnonzero(self._active):
            req = self._slot_req[slot]
            tok = int(nxt[slot])
            self._tok[slot] = tok
            # copy, not view: a view would alias the mirror row, and a later
            # admission writing another request's key into this slot would
            # silently corrupt THIS request's key stream after preemption
            req.key = self._keys[slot].copy()
            self._emit_token(req, tok, now)
            self._maybe_finish(req, now)

    # --- lifecycle helpers --------------------------------------------------

    def _emit_token(self, req: Request, tok: int, now: float,
                    first: bool = False) -> None:
        req.tokens.append(tok)
        if first:
            req.first_token_time = now
            self.metrics.record_first_token(req, now)
        cb = self._on_token.get(req.rid)
        if cb is not None:
            cb(req, tok)

    def _maybe_finish(self, req: Request, now: float) -> None:
        if req.state is RequestState.CANCELLED:
            # e.g. an on_token callback cancelled it this very step: the
            # cancellation wins; _reap_cancelled retires the slot next step
            return
        eos = req.config.eos_token_id
        hit_eos = eos is not None and req.tokens and req.tokens[-1] == eos
        if hit_eos or len(req.tokens) >= req.config.max_new_tokens:
            req.state = RequestState.DONE
            req.finish_time = now
            self.metrics.record_finish(req, now)
            self._release_slot(req)
            if self.timeline is not None:
                self.timeline.instant(f"done r{req.rid}", "serving")

    def _release_slot(self, req: Request) -> None:
        slot = req.slot
        if slot is None:
            return
        req.slot = None
        self._slot_req[slot] = None
        self._active[slot] = False
        self.cache.free(slot)
        self._on_token.pop(req.rid, None)

    def _reap_cancelled(self, now: float) -> None:
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.state is RequestState.CANCELLED:
                self.metrics.record_cancel(req, now)
                req.finish_time = now
                self._release_slot(req)

    def _preempt_all(self) -> None:
        """Out of cache columns: push every active request back to the queue
        (keeping its generated tokens and current key), rewind the cache,
        and let admission re-prefill their contexts. Token streams are
        unaffected — resume replays the exact context the request had."""
        preempted = [r for r in self._slot_req if r is not None]
        for req in preempted:
            req.preemptions += 1
            self.metrics.record_preemption(req)
            slot, req.slot = req.slot, None
            self._slot_req[slot] = None
            self._active[slot] = False
            self.cache.free(slot)
        self.scheduler.requeue_front(preempted)
        self.cache.reset()
        if self.timeline is not None:
            self.timeline.instant(
                f"preempt x{len(preempted)}", "serving"
            )

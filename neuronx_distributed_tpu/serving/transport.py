"""Message seam for the elastic serving fabric.  # graftlint: hot-path

Every router→replica and disagg handoff interaction (submit, adopt,
page-span export/import, probe, restore) rides a :class:`Transport`
instead of a bare Python method call.  Two implementations ship:

* :class:`InProcessTransport` — the production default.  With no faults
  the target callable runs synchronously exactly once and its result is
  returned unchanged, so behaviour is **bit-identical to a direct
  call**; the only additions are the envelope bookkeeping (host-side
  integer arithmetic) and the idempotency cache.
* :class:`ChaosTransport` — consults a
  :class:`~neuronx_distributed_tpu.serving.faults.FaultInjector` before
  each delivery attempt and deterministically drops, duplicates, delays
  or partitions messages by send index.

Reliability contract
--------------------
Each logical message is wrapped in an :class:`Envelope` carrying an
``(rid, seq)`` idempotency key — ``seq`` is minted ONCE per
:meth:`Transport.call`, so every retry of the same logical message
reuses the same key while a genuinely new message gets a fresh one.
Delivery consults a bounded dedup cache keyed by
``(target, op, rid, seq)``: a duplicated or retried delivery whose
first attempt already ran returns the **cached outcome** (result or
application exception) without invoking the target again.  That is the
exactly-once guarantee the fabric leans on: a retried or duplicated
handoff can never double-admit a request or double-count tokens.

Sends ride :func:`~neuronx_distributed_tpu.utils.retry.with_retries`
with ``TransportError`` transient; an optional per-message deadline
(``deadline_s``) is checked before every attempt and raises
:class:`TransportTimeout` — a *passthrough* error, because once the
deadline has lapsed more retries cannot help.

Everything here is host-side scalar bookkeeping — no device values ever
enter an envelope, so the seam adds zero host↔device syncs.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from neuronx_distributed_tpu.utils.retry import RetryPolicy, with_retries

__all__ = [
    "Envelope",
    "TransportError",
    "TransportTimeout",
    "PartitionedError",
    "InProcessTransport",
    "ChaosTransport",
    "DEFAULT_TRANSPORT_RETRY",
]


class TransportError(RuntimeError):
    """A send failed in flight (drop, lost ack, partition) — retryable."""


class TransportTimeout(TransportError):
    """The message's deadline lapsed before delivery — NOT retried."""


class PartitionedError(TransportError):
    """The target is unreachable (network partition window) — retryable,
    but every attempt inside the window fails the same way."""


@dataclasses.dataclass(frozen=True)
class Envelope:
    """One logical message.  ``(rid, seq)`` is the idempotency key —
    ``seq`` is per-transport monotone and minted once per logical
    message, so retries reuse it and duplicates are detectable.
    ``deadline`` is absolute on the transport's clock (None = no
    deadline)."""

    op: str
    rid: int
    seq: int
    deadline: Optional[float] = None


# Waits are tiny because the in-process "network" recovers instantly —
# the retry count (5 attempts) is what matters for riding out bounded
# fault windows, not the backoff duration.  sleep defaults to a no-op
# so retries are free under virtual-clock tests.
DEFAULT_TRANSPORT_RETRY = RetryPolicy(max_attempts=5, first_wait=0.02, min_wait=0.005)

_PROBE_RETRY = RetryPolicy(max_attempts=1, first_wait=0.0, min_wait=0.0)


class InProcessTransport:
    """Default transport: synchronous local delivery, exactly once.

    ``time_fn`` supplies the clock deadlines are checked against (share
    the engines' virtual clock in tests).  ``sleep_fn`` is called with
    each retry backoff — default no-op, since an in-process resend has
    nothing to wait for.  The dedup cache is bounded (FIFO eviction at
    ``dedup_capacity`` entries) — old outcomes age out long after any
    retry of their message could still arrive.
    """

    #: True on transports that may inject faults — lets callers log it.
    faulty = False

    def __init__(
        self,
        time_fn: Callable[[], float] = time.monotonic,
        sleep_fn: Optional[Callable[[float], None]] = None,
        retry: RetryPolicy = DEFAULT_TRANSPORT_RETRY,
        dedup_capacity: int = 4096,
    ):
        if dedup_capacity < 1:
            raise ValueError(f"dedup_capacity must be >= 1, got {dedup_capacity}")
        self._clock = time_fn
        self._sleep = sleep_fn if sleep_fn is not None else (lambda _s: None)
        self.retry = retry
        self._seq = 0
        self._send_idx = 0  # delivery attempts, across all messages
        self._dedup: "OrderedDict[Tuple[Any, str, int, int], Tuple[str, Any]]" = OrderedDict()
        self._dedup_capacity = int(dedup_capacity)
        self.stats: Dict[str, int] = {
            "messages": 0,
            "deliveries": 0,
            "retries": 0,
            "drops": 0,
            "ack_drops": 0,
            "dup_deliveries": 0,
            "delays": 0,
            "timeouts": 0,
            "partitioned": 0,
            "dedup_hits": 0,
            "give_ups": 0,
        }

    # --- sending ------------------------------------------------------------

    def call(
        self,
        target: Any,
        op: str,
        fn: Callable[[], Any],
        *,
        rid: int = -1,
        deadline_s: Optional[float] = None,
        retry: Optional[RetryPolicy] = None,
    ) -> Any:
        """Send one logical message: run ``fn`` at ``target`` and return
        its result.  ``target`` is any hashable address (replica index,
        ``"decode"``, ...).  ``rid`` attributes the message to a request
        for the idempotency key; ``deadline_s`` bounds total delivery
        time from now; ``retry`` overrides the transport policy (probes
        pass a single-attempt policy so one probe = one verdict).

        Application exceptions raised by ``fn`` propagate unchanged (and
        are cached, so a duplicate delivery re-raises without re-running
        ``fn``).  :class:`TransportError` is retried per policy;
        :class:`TransportTimeout` is terminal.
        """
        self._seq += 1
        now = self._clock()
        env = Envelope(
            op=op,
            rid=int(rid),
            seq=self._seq,
            deadline=None if deadline_s is None else now + float(deadline_s),
        )
        self.stats["messages"] += 1
        attempts = 0

        def _once():
            nonlocal attempts
            if attempts:
                self.stats["retries"] += 1
            attempts += 1
            if env.deadline is not None and self._clock() > env.deadline:
                self.stats["timeouts"] += 1
                raise TransportTimeout(
                    f"transport {env.op} to {target!r} (rid={env.rid}, "
                    f"seq={env.seq}) missed its {deadline_s}s deadline"
                )
            return self._attempt(target, env, fn)

        try:
            return with_retries(
                _once,
                what=f"transport {op} -> {target!r}",
                policy=self.retry if retry is None else retry,
                transient=(TransportError,),
                passthrough=(TransportTimeout,),
                sleep=self._sleep,
            )
        except TransportError:
            self.stats["give_ups"] += 1
            raise

    def probe(self, target: Any, fn: Callable[[], Any], *, deadline_s: Optional[float] = None) -> Any:
        """A single-attempt health probe: one send, one verdict — probe
        retrying is the watchdog's job (that's what its consecutive-
        failure thresholds count)."""
        return self.call(target, "probe", fn, deadline_s=deadline_s, retry=_PROBE_RETRY)

    # --- delivery -----------------------------------------------------------

    def _attempt(self, target: Any, env: Envelope, fn: Callable[[], Any]) -> Any:
        """One delivery attempt.  Subclasses inject faults here."""
        self._send_idx += 1
        return self._deliver(target, env, fn)

    def _deliver(self, target: Any, env: Envelope, fn: Callable[[], Any]) -> Any:
        key = (target, env.op, env.rid, env.seq)
        hit = self._dedup.get(key)
        if hit is not None:
            # Exactly-once: this (rid, seq) already ran — hand back the
            # recorded outcome instead of invoking the target again.
            self.stats["dedup_hits"] += 1
            kind, value = hit
            if kind == "raise":
                raise value
            return value
        self.stats["deliveries"] += 1
        try:
            result = fn()
        except Exception as e:  # application outcome — cached, never retried
            self._remember(key, ("raise", e))
            raise
        self._remember(key, ("ok", result))
        return result

    def _remember(self, key, outcome: Tuple[str, Any]) -> None:
        self._dedup[key] = outcome
        while len(self._dedup) > self._dedup_capacity:
            self._dedup.popitem(last=False)

    # --- introspection ------------------------------------------------------

    def snapshot(self) -> Dict[str, int]:
        out = dict(self.stats)
        out["dedup_entries"] = len(self._dedup)
        return out


class ChaosTransport(InProcessTransport):
    """Fault-injecting transport: before each delivery attempt it asks
    ``faults.on_transport_send(send_idx, target, op)`` for an action —
    drop the send, drop only the ack (deliver, then report failure so
    the sender retries into the dedup cache), duplicate the delivery,
    delay it against the message deadline, or fail the whole window as
    a partition.  ``send_idx`` is the transport-wide monotone attempt
    counter, so a fault schedule is deterministic for a deterministic
    workload."""

    faulty = True

    def __init__(self, faults, **kwargs):
        super().__init__(**kwargs)
        self.faults = faults

    def _attempt(self, target: Any, env: Envelope, fn: Callable[[], Any]) -> Any:
        idx = self._send_idx
        self._send_idx += 1
        action = None
        if self.faults is not None:
            action = self.faults.on_transport_send(idx, target, env.op)
        if action is None:
            return self._deliver(target, env, fn)
        kind = action[0]
        if kind == "partition":
            self.stats["partitioned"] += 1
            raise PartitionedError(
                f"target {target!r} unreachable: partition window (send {idx})"
            )
        if kind == "drop":
            self.stats["drops"] += 1
            raise TransportError(
                f"transport {env.op} to {target!r} dropped in flight (send {idx})"
            )
        if kind == "delay":
            by = float(action[1])
            self.stats["delays"] += 1
            if env.deadline is not None and self._clock() + by > env.deadline:
                self.stats["timeouts"] += 1
                raise TransportTimeout(
                    f"transport {env.op} to {target!r} delayed {by}s past its "
                    f"deadline (send {idx})"
                )
            self._sleep(by)
            return self._deliver(target, env, fn)
        if kind == "drop_ack":
            # The request reached the target and ran — only the reply was
            # lost.  The sender must retry and land in the dedup cache;
            # this is the scenario that makes exactly-once load-bearing.
            result_ok = False
            try:
                self._deliver(target, env, fn)
                result_ok = True
            finally:
                if result_ok:
                    self.stats["ack_drops"] += 1
            raise TransportError(
                f"ack for {env.op} to {target!r} (rid={env.rid}, "
                f"seq={env.seq}) lost (send {idx})"
            )
        if kind == "dup":
            # The network delivered the same envelope twice; the second
            # copy must hit the dedup cache, not the application — and it
            # arrives regardless of how the first copy's outcome went, so
            # it replays a cached app exception rather than re-running.
            self.stats["dup_deliveries"] += 1
            try:
                self._deliver(target, env, fn)
            except Exception:
                pass
            return self._deliver(target, env, fn)
        raise ValueError(f"unknown transport fault action {action!r}")

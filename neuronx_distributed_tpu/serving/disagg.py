"""Disaggregated prefill/decode serving (ISSUE 14).

A coupled engine runs prefill INSIDE its serving loop: at a chunk boundary
it admits a whole selection round, paying every prefill's wall before the
next decode chunk dispatches. Under bursty arrivals that is exactly when a
burst lands — steady-state decoders stall behind a queue of prompt
prefills, and TPOT p99 inflates with the arrival rate (measured by
``bench.py --child-multichip`` replaying the ISSUE 11 bursty tape through
both topologies).

This module splits the two phases:

* :class:`PrefillWorker` owns the bucketed prefill programs — a dedicated
  worker (its own jitted programs, its own fault domain; on real hardware
  its own chips) that turns a queued request into a prefilled context.
* :class:`DisaggregatedServer` fronts a PAGED decode engine
  (``external_prefill=True`` — the engine never self-admits): it pulls
  queued requests, prefills them on workers (at most
  ``prefills_per_step`` per loop iteration, the knob that bounds how much
  prefill wall can ever sit between two decode chunks), and hands each
  finished context to the engine as a PAGE-TABLE handoff.

The handoff is the PR 9 payoff: the worker stages the context's K/V pages
directly in the decode engine's pool
(:meth:`~neuronx_distributed_tpu.serving.paging.PagedCacheManager.
stage_context`), and ``ServingEngine.admit_staged`` binds them to a slot by
block-table mapping plus one metadata program — zero KV bytes move,
``PageAllocator.copy_bytes`` stays 0 (acceptance-pinned). When prefill and
decode pools are DISTINCT (different hosts/meshes), the explicit
``export_pages()/import_pages()`` device transfer is the fallback, and the
copy is charged to ``copy_bytes`` — the accounting that proves the
shared-pool path moved nothing.

Fault contract (chaos-tested in tests/serving/test_disagg.py): a worker
whose prefill raises leaves the rotation and its request falls back to
COUPLED prefill on the decode engine (no workers left → the server flips
the engine back to self-admission entirely); a failed handoff
(``FaultInjector.fail_handoff``) releases the staged pages and falls back
the same way. Streams stay bit-identical in every case — the fallback is
the very program a coupled engine runs.

With a ``transport=`` (ISSUE 18) every prefill→decode interaction — the
handoff admit, the distinct-pool page export/import — becomes a message
on the elastic-fabric seam: retried under the transport's policy, deduped
by ``(rid, seq)`` so a duplicated handoff can never double-admit, and a
message the ChaosTransport drops lands in the SAME coupled-fallback path
an injected handoff failure does. The server also speaks the router's
replica surface (``adopt``/``fence``/``load_score``/…), so a
disaggregated server can sit behind a :class:`~neuronx_distributed_tpu.
serving.router.ReplicaRouter` and its watchdog; ``fence()`` first
releases pending staged pages and requeues their requests so the re-home
path sees every unfinished request in the queue.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax

from neuronx_distributed_tpu.inference.generate import (
    pack_padded_prompt,
    serving_clones,
)
from neuronx_distributed_tpu.inference.utils import unwrap_logits
from neuronx_distributed_tpu.serving.engine import ServingEngine, _bucket
from neuronx_distributed_tpu.serving.paging import (
    PagedCacheManager,
    StagedContext,
)
from neuronx_distributed_tpu.serving.scheduler import Request, RequestState


class PrefillWorker:
    """One dedicated prefill worker: the bucketed prefill programs for the
    decode engine's model, staging results into ``pool`` (the decode
    engine's pool on the shared-pool path; the worker's own pool on the
    distinct-pool path, where the server exports/imports)."""

    def __init__(self, model, params, pool: PagedCacheManager,
                 label: str = "prefill0", fault_injector=None,
                 programs=None):
        self.label = label
        self._prefill_model, _ = serving_clones(model)
        self._params = dict(params)
        self.pool = pool
        self._faults = fault_injector
        self._programs = programs
        self._fns: Dict[int, Callable] = {}
        self.calls = 0

    def _fn(self, padded_len: int):
        fn = self._fns.get(padded_len)
        if fn is None:
            prefill = self._prefill_model

            @jax.jit
            def fn(params, ids, mask):
                out, variables = prefill.apply(
                    params, ids, padding_mask=mask, mutable=["cache"]
                )
                return unwrap_logits(out)[0, -1], variables["cache"]

            if self._programs is not None:
                fn = self._programs.wrap(
                    f"{self.label}_prefill[{padded_len}]", fn
                )
            self._fns[padded_len] = fn
        return fn

    def prefill(self, req: Request, max_seq_len: int):
        """Run the bucketed prefill for ``req`` and stage the context in
        ``pool``. Returns ``(staged, logits)`` — the page-table handoff
        unit plus the last-token logits the decode side samples the first
        token from. Raises whatever the prefill raises (the server's
        worker-failure path)."""
        call = self.calls
        self.calls += 1
        if self._faults is not None:
            self._faults.on_prefill(call)
        ctx = req.context_ids
        p = len(ctx)
        padded = _bucket(p, max_seq_len, req.remaining_new_tokens)
        ids, mask = pack_padded_prompt(ctx, padded)
        import jax.numpy as jnp

        logits, row_cache = self._fn(padded)(
            self._params, jnp.asarray(ids), jnp.asarray(mask)
        )
        staged = self.pool.stage_context(row_cache, p, padded)
        return staged, logits

    @property
    def prefill_compilations(self) -> int:
        return sum(int(fn._cache_size()) for fn in self._fns.values())


class DisaggregatedServer:
    """Prefill/decode disaggregation facade over one paged decode engine.

    Drives the same ``submit()/step()/run()`` surface as the engine (and
    the traffic-replay harness: ``metrics``/``scheduler``/``_clock``
    forward), so coupled-vs-disaggregated comparisons swap one object."""

    def __init__(self, engine: ServingEngine, n_workers: int = 1,
                 prefills_per_step: int = 1, shared_pool: bool = True,
                 fault_injector=None, transport=None):
        if engine._page_size is None:
            raise ValueError(
                "disaggregation needs a PAGED decode engine "
                "(kv_page_size=) — the handoff is a block-table operation"
            )
        if engine.draft_model is not None:
            raise ValueError(
                "disaggregation does not speak speculative engines yet "
                "(the draft cache would need its own handoff)"
            )
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if prefills_per_step < 1:
            raise ValueError(
                f"prefills_per_step must be >= 1, got {prefills_per_step}"
            )
        self.engine = engine
        self.shared_pool = shared_pool
        self.prefills_per_step = prefills_per_step
        self._faults = fault_injector
        self.transport = transport
        engine.external_prefill = True
        self.workers: List[PrefillWorker] = []
        for i in range(n_workers):
            pool = engine.cache if shared_pool else PagedCacheManager(
                1, engine.max_seq_len, engine._page_size,
                engine.cache.alloc.num_pages,
            )
            self.workers.append(
                PrefillWorker(
                    engine.model, engine._params, pool,
                    label=f"prefill{i}", fault_injector=fault_injector,
                    programs=engine.programs,
                )
            )
        self._rotation = 0
        # completed prefills awaiting a chunk-boundary handoff
        self._pending: List[tuple] = []
        self._handoff_attempts = 0
        self.stats: Dict[str, int] = {
            "prefills": 0,
            "handoffs": 0,
            "handoff_failures": 0,
            "worker_failures": 0,
            "coupled_fallbacks": 0,
            "imported_contexts": 0,
        }

    # --- engine surface (traffic replay compatibility) ----------------------

    @property
    def metrics(self):
        return self.engine.metrics

    @property
    def scheduler(self):
        return self.engine.scheduler

    @property
    def _clock(self):
        return self.engine._clock

    def health(self):
        return self.engine.health()

    def submit(self, *args, **kwargs) -> Request:
        return self.engine.submit(*args, **kwargs)

    @property
    def has_work(self) -> bool:
        return bool(self._pending) or self.engine.has_work

    # --- router replica surface (ISSUE 18) ----------------------------------
    # a DisaggregatedServer can stand behind a ReplicaRouter: balancing,
    # affinity, watchdog probes, and re-homing all speak these

    @property
    def prefix(self):
        return self.engine.prefix

    @property
    def flight(self):
        return self.engine.flight

    @property
    def _on_token(self):
        return self.engine._on_token

    @property
    def _next_rid(self):
        return self.engine._next_rid

    def load_score(self, tenant: Optional[str] = None) -> float:
        return self.engine.load_score(tenant=tenant)

    def page_pressure(self) -> float:
        return self.engine.page_pressure()

    def adopt(self, req: Request, on_token=None) -> Request:
        return self.engine.adopt(req, on_token=on_token)

    def release_queued(self, rid: int):
        return self.engine.release_queued(rid)

    def drain(self) -> None:
        self.engine.drain()

    def resume(self) -> None:
        self.engine.resume()

    def fence(self, reason: str = "fenced") -> None:
        """Kill switch through the engine's halt contract, made
        handoff-aware: contexts prefilled but not yet admitted release
        their staged pages and their requests rejoin the queue FIRST, so
        the post-fence queue (what a router re-homes) holds every
        unfinished request — none marooned in ``_pending``."""
        pending, self._pending = self._pending, []
        for req, staged, _logits in pending:
            self._release(staged, self.engine.cache)
            if not req.finished:
                self.engine.scheduler.requeue_front([req])
        self.engine.fence(reason)

    # --- transport seam (ISSUE 18) ------------------------------------------

    def _send(self, target: str, op: str, fn, rid: int = -1):
        """Route one prefill/decode interaction over the fabric transport
        (retries + ``(rid, seq)`` dedup), or call directly when no
        transport is bound — bit-identical either way."""
        if self.transport is None:
            return fn()
        return self.transport.call(target, op, fn, rid=rid)

    # --- the serving loop ----------------------------------------------------

    def _coupled_fallback(self, req: Request, now: float) -> None:
        """Prefill ``req`` on the DECODE engine's own coupled path — the
        exact program a non-disaggregated engine runs, so the stream is
        bit-identical; no free slot right now just requeues it."""
        self.stats["coupled_fallbacks"] += 1
        if req.finished:
            return
        if self.engine.cache.free_slots == 0:
            self.engine.scheduler.requeue_front([req])
            return
        self.engine._prefill_into_slot(
            req, self.engine.cache.acquire(), now
        )

    def _release(self, staged: Optional[StagedContext], pool) -> None:
        if staged is not None and staged.page_ids:
            pool.release_staged(staged)

    def _try_handoffs(self, now: float) -> None:
        still: List[tuple] = []
        for req, staged, logits in self._pending:
            if req.finished:  # cancelled/shed while pending
                self._release(staged, self.engine.cache)
                continue
            if req.deadline is not None and now >= req.deadline:
                self._release(staged, self.engine.cache)
                req.state = RequestState.TIMED_OUT
                req.error = "deadline exceeded awaiting handoff"
                req.finish_time = now
                self.engine.metrics.record_shed(req, now, where="queue")
                self.engine.tracer.end(
                    req.rid, "shed",
                    args={"where": "handoff", "tenant": req.tenant},
                )
                continue
            attempt = self._handoff_attempts
            self._handoff_attempts += 1
            try:
                if self._faults is not None:
                    self._faults.on_handoff(attempt)
                admitted = self._send(
                    "decode", "handoff",
                    lambda r=req, s=staged, lg=logits:
                        self.engine.admit_staged(r, s, lg, now),
                    rid=req.rid,
                )
            except Exception:
                # injected handoff failure, an undeliverable handoff
                # message (transport gave up), or a staged context voided
                # by pool recovery/page quarantine: nothing is half-mapped
                # — release the pages and fall back to coupled prefill
                self.stats["handoff_failures"] += 1
                self._release(staged, self.engine.cache)
                self._coupled_fallback(req, now)
                continue
            if admitted:
                self.stats["handoffs"] += 1
            else:
                still.append((req, staged, logits))
        self._pending = still

    def _run_prefills(self, now: float) -> None:
        if not self.workers:
            return
        budget = self.prefills_per_step
        while budget > 0 and self.engine.scheduler.queued > 0:
            pending_tokens = sum(
                r.token_footprint for r, _, _ in self._pending
            )
            selected = self.engine.scheduler.select(
                1,
                self.engine._in_flight_tokens() + pending_tokens,
                fits=None,
            )
            if not selected:
                break
            req = selected[0]
            budget -= 1
            worker = self.workers[self._rotation % len(self.workers)]
            self._rotation += 1
            try:
                staged, logits = worker.prefill(req, self.engine.max_seq_len)
            except Exception:
                # the worker is now suspect: pull it from the rotation and
                # serve this request through the coupled path. Losing the
                # last worker flips the engine back to full self-admission
                # — disaggregation degrades to a coupled engine, never to
                # an outage
                self.stats["worker_failures"] += 1
                try:
                    self.workers.remove(worker)
                except ValueError:
                    pass
                if not self.workers:
                    self.engine.external_prefill = False
                self._coupled_fallback(req, now)
                continue
            if not self.shared_pool:
                # distinct pools: explicit device transfer — charged to
                # the decode pool's copy_bytes, unlike the shared path
                # which moves nothing. Transfer failures are HANDOFF
                # failures, never worker failures: a transient
                # PageExhausted on the decode pool must not dismantle a
                # healthy worker — this request just prefills coupled
                # (whose own page-pressure machinery absorbs it)
                try:
                    exported = self._send(
                        "prefill", "page_export",
                        lambda w=worker, s=staged: w.pool.export_pages(s),
                        rid=req.rid,
                    )
                except Exception:
                    self._release(staged, worker.pool)
                    self.stats["handoff_failures"] += 1
                    self._coupled_fallback(req, now)
                    continue
                self._release(staged, worker.pool)
                try:
                    if self.engine.cache.cache is None:
                        self.engine.cache.allocate_like(worker.pool)
                    staged = self._send(
                        "decode", "page_import",
                        lambda e=exported: self.engine.cache.import_pages(e),
                        rid=req.rid,
                    )
                except Exception:
                    self.stats["handoff_failures"] += 1
                    self._coupled_fallback(req, now)
                    continue
                self.stats["imported_contexts"] += 1
            self.stats["prefills"] += 1
            self._pending.append((req, staged, logits))

    def step(self) -> bool:
        """One disaggregated iteration: bind completed handoffs (cheap
        page-table ops), run the decode engine's step (its chunk never
        waits on a prefill), then run at most ``prefills_per_step`` worker
        prefills — the bound on prefill wall between chunks that a coupled
        engine does not have."""
        now = self.engine._now()
        self._try_handoffs(now)
        self.engine.step()
        self._run_prefills(self.engine._now())
        return self.has_work

    def run(self, max_steps: int = 1_000_000) -> Dict[int, Request]:
        steps = 0
        while self.has_work and steps < max_steps:
            self.step()
            steps += 1
        return {
            r.rid: r for r in self.engine.scheduler.requests.values()
        }

    def snapshot(self) -> dict:
        return {
            "disagg": {
                **self.stats,
                "workers_live": len(self.workers),
                "pending_handoffs": len(self._pending),
                "copy_bytes": self.engine.cache.alloc.copy_bytes,
            },
            "engine": self.engine.metrics.snapshot(),
        }

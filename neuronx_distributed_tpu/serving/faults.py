"""Deterministic fault injection for the serving engine.

Chaos testing a donated, device-resident decode path needs failures that are
*schedulable*, not random: a dispatch that raises at exactly chunk ``k``, a
readback poisoned for exactly slot ``s``, a prefill that OOMs on exactly the
``n``-th admission, a clock that jumps mid-run. ``FaultInjector`` is a
passive schedule the engine consults at its four hook points; with no
injector (the default) every hook is a no-op and the hot path is untouched.

Injection points (all indices are 0-based and deterministic):

* ``fail_dispatch(at=k, times=t)`` — the k-th..(k+t-1)-th decode *dispatch
  attempts* raise ``InjectedDispatchError`` before the jitted chunk runs
  (the donated buffers are NOT consumed, mirroring a host-side enqueue
  failure). ``times=None`` fails every attempt from ``at`` on — the way to
  drive the engine into ``HALTED``.
* ``poison_readback(at=k, slot=s, token=v)`` — mutates the host token block
  of the k-th *successful* readback: slot ``s``'s first token becomes ``v``
  (out-of-vocab by default), modeling a corrupted device buffer. Neighbor
  slots' columns are untouched, so isolation is testable. If slot ``s`` is
  not active at readback ``k`` the poison DEFERS to the next readback
  (firing into an empty slot would prove nothing).
* ``fail_prefill(at=n, times=t)`` — the n-th prefill call raises
  ``InjectedPrefillError`` (an OOM-like admission failure).
* ``poison_prefix(at=k, times=t)`` — corrupts the STORED prefix-cache entry
  the k-th prefix *reuse attempt* is about to copy from (every float leaf of
  its KV block is perturbed), modeling silent corruption of host-managed
  prefix storage. The engine's reuse-time checksum/shape validation must
  evict the entry and fall back to a full prefill — poisoned KV must never
  reach a slot.
* ``poison_page(at=k, slot=s)`` — PAGED engines (``kv_page_size=``): at the
  k-th successful readback, the first pool page mapped by slot ``s``'s
  block table is declared poisoned. The engine must quarantine THE PAGE
  (retire it from the pool) and requeue exactly the requests whose tables
  map it — CoW sharers included, neighbors untouched, the slot index
  itself back in rotation. Defers like ``poison_readback`` when the slot
  is not active (or maps nothing) at that readback.
* ``skew_clock(by=s)`` / ``skew_clock(by=s, after=t)`` — the engine clock
  reads ``s`` seconds ahead (optionally only once real time passes
  ``after``), driving deadline/queue-timeout shedding paths without
  sleeping.
* ``fail_draft_dispatch(at=k, times=t)`` — the k-th speculative dispatch
  attempts raise ``InjectedDraftError`` before the fused draft–verify chunk
  runs; the engine decodes the affected chunk non-speculatively (streams
  bit-identical) and resyncs the draft cache.
* ``poison_draft(at=k, times=t)`` — the k-th speculative dispatches run
  with a corrupted COPY of the draft params (mid-chunk all-reject rounds:
  every proposal garbage); the stream must stay bit-identical regardless.
* ``fail_spill(at=k, times=t)`` — TIERED engines (``kv_host_pages=``): the
  k-th..(k+t-1)-th spill attempts raise ``InjectedSpillError`` before the
  device->host pull runs. The engine must degrade to plain eviction (the
  pre-tiering reclaim behavior) — never a leak, never a crash, streams
  untouched.
* ``fail_prefetch(at=k, times=t)`` — the k-th prefetch attempts raise
  ``InjectedPrefetchError`` before anything is written device-side. The
  engine must drop the host-tier entry and fall back to a full prefill,
  bit-identically (K/V is position-relative — re-prefilling the tokens
  rebuilds the same pages).
* ``poison_host_page(at=k, times=t)`` — the k-th prefetch attempts find
  their entry's FIRST host page corrupted in place (one byte flipped),
  modeling host-RAM bit rot. The store's fingerprint verification must
  reject the whole fetch and the engine must fall back to a full prefill
  — corrupted host bytes never reach the pool.
* ``flip_bits(target=..., at=k, times=t)`` — SILENT data corruption
  (ISSUE 20): flips ONE low-order bit, numerically near-invisible, so no
  loud guard (readback garbage, NaN logits) ever fires and only bit-level
  integrity fingerprints can catch it. ``target="params"`` corrupts the
  engine's bound weights at the start of the k-th..(k+t-1)-th ``step()``
  calls — the replica keeps serving plausibly-wrong tokens until the
  router's cross-replica fingerprint vote fences it. ``target="kv_pool"``
  corrupts the first pool page of the entry the k-th prefix *reuse
  attempt* maps (before validation) — the engine's per-page fingerprint
  check must reject the reuse and fall back to a full prefill.
* ``drop_send / drop_ack / dup_send / delay_send / partition`` — transport
  fault schedules consulted by ``serving/transport.ChaosTransport`` per
  delivery-attempt index (transport-wide monotone, so deterministic for a
  deterministic workload): drop the k-th send in flight, deliver the k-th
  send but lose its ack (forcing a retry into the idempotency cache),
  deliver the k-th send twice, delay it against its message deadline, or
  make a specific target unreachable for a window of sends.

``counters`` records every fault actually fired so chaos tests can assert
the schedule ran (an injection that never fired proves nothing).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class InjectedFault(RuntimeError):
    """Base class for injected failures (never raised by real code paths)."""


class InjectedDispatchError(InjectedFault):
    """Scheduled decode-dispatch failure."""


class InjectedDraftError(InjectedFault):
    """Scheduled SPECULATIVE decode-dispatch failure (the draft side of a
    fused draft–verify chunk): the engine must fall back to non-speculative
    decode for the affected chunk, streams bit-identical."""


class InjectedPrefillError(InjectedFault):
    """Scheduled prefill failure (OOM-like admission fault)."""


class InjectedHandoffError(InjectedFault):
    """Scheduled DISAGGREGATED handoff failure (ISSUE 14): the page-table
    transfer between a prefill worker and the decode engine fails — the
    disaggregation server must fall back to coupled prefill on the decode
    engine, streams bit-identical, zero tokens lost."""


class InjectedSpillError(InjectedFault):
    """Scheduled KV spill failure (ISSUE 19): the device->host pull of a
    cold prefix entry's pages fails — the engine must degrade to plain
    eviction, never a leak or a crash."""


class InjectedPrefetchError(InjectedFault):
    """Scheduled KV prefetch failure (ISSUE 19): the host->device re-home
    of a spilled prefix entry fails — the engine must drop the host copy
    and fall back to a full prefill, bit-identically."""


class FaultInjector:
    """Schedule-driven fault source consulted by ``ServingEngine`` hooks."""

    def __init__(self):
        # [at, end) half-open attempt windows; end=None → open-ended
        self._dispatch_windows: List[Tuple[int, Optional[int]]] = []
        self._poisons: Dict[int, List[Tuple[int, int]]] = {}  # readback -> [(slot, token)]
        self._prefill_windows: List[Tuple[int, Optional[int]]] = []
        self._prefix_windows: List[Tuple[int, Optional[int]]] = []
        self._draft_dispatch_windows: List[Tuple[int, Optional[int]]] = []
        self._draft_poison_windows: List[Tuple[int, Optional[int]]] = []
        self._handoff_windows: List[Tuple[int, Optional[int]]] = []
        self._page_poisons: Dict[int, List[int]] = {}  # readback -> [slot]
        # tiered KV (ISSUE 19), keyed by spill / prefetch attempt index
        self._spill_windows: List[Tuple[int, Optional[int]]] = []
        self._prefetch_windows: List[Tuple[int, Optional[int]]] = []
        self._host_page_windows: List[Tuple[int, Optional[int]]] = []
        # silent bit flips (ISSUE 20): params keyed by engine step index,
        # kv_pool keyed by prefix reuse-attempt index
        self._params_flip_windows: List[Tuple[int, Optional[int]]] = []
        self._pool_flip_windows: List[Tuple[int, Optional[int]]] = []
        # transport fault schedules, all keyed by delivery-attempt index
        self._send_drops: List[Tuple[int, Optional[int]]] = []
        self._ack_drops: List[Tuple[int, Optional[int]]] = []
        self._send_dups: List[Tuple[int, Optional[int]]] = []
        self._send_delays: List[Tuple[int, Optional[int], float]] = []
        self._partitions: Dict[object, List[Tuple[int, Optional[int]]]] = {}
        self._skew: float = 0.0
        self._skew_after: Optional[float] = None
        self.counters: Dict[str, int] = {
            "dispatch_failures": 0,
            "poisoned_readbacks": 0,
            "prefill_failures": 0,
            "poisoned_prefixes": 0,
            "draft_dispatch_failures": 0,
            "poisoned_drafts": 0,
            "poisoned_pages": 0,
            "handoff_failures": 0,
            "spill_failures": 0,
            "prefetch_failures": 0,
            "poisoned_host_pages": 0,
            "bit_flips": 0,
            "dropped_sends": 0,
            "dropped_acks": 0,
            "dup_sends": 0,
            "delayed_sends": 0,
            "partitioned_sends": 0,
        }

    # --- schedule construction ----------------------------------------------

    def fail_dispatch(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        end = None if times is None else at + times
        self._dispatch_windows.append((at, end))
        return self

    def poison_readback(self, at: int, slot: int, token: int = -1) -> "FaultInjector":
        self._poisons.setdefault(at, []).append((slot, token))
        return self

    def fail_prefill(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        end = None if times is None else at + times
        self._prefill_windows.append((at, end))
        return self

    def poison_prefix(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        end = None if times is None else at + times
        self._prefix_windows.append((at, end))
        return self

    def fail_draft_dispatch(
        self, at: int = 0, times: Optional[int] = 1
    ) -> "FaultInjector":
        """The ``at``-th..(at+times-1)-th SPECULATIVE dispatch attempts
        raise :class:`InjectedDraftError` before the fused draft–verify
        chunk runs (donated buffers unconsumed, mirroring a host-side
        enqueue failure on the draft program). The engine must decode the
        affected chunk NON-speculatively — streams bit-identical, zero
        tokens lost — then resync the draft cache."""
        end = None if times is None else at + times
        self._draft_dispatch_windows.append((at, end))
        return self

    def poison_draft(
        self, at: int = 0, times: Optional[int] = 1
    ) -> "FaultInjector":
        """Corrupt the DRAFT params the ``at``-th..(at+times-1)-th
        speculative dispatches use (every float leaf perturbed on a copy —
        the engine's bound pytree is untouched), driving mid-chunk
        all-reject rounds: every proposal garbage, every round emitting
        only its correction. The test this exists for: the stream must
        stay bit-identical anyway (speculation's output never depends on
        draft quality)."""
        end = None if times is None else at + times
        self._draft_poison_windows.append((at, end))
        return self

    def poison_page(self, at: int = 0, times: int = 1,
                    slot: int = 0) -> "FaultInjector":
        """At the ``at``-th..(at+times-1)-th successful readbacks of a
        PAGED engine, poison the first pool page slot ``slot``'s block
        table maps — modeling one corrupted HBM page. The engine's
        page-granular quarantine must retire the page and requeue exactly
        the requests mapping it (bit-identically), nothing else."""
        for i in range(times):
            self._page_poisons.setdefault(at + i, []).append(slot)
        return self

    def fail_handoff(self, at: int = 0,
                     times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th..(at+times-1)-th disaggregated HANDOFF attempts
        raise :class:`InjectedHandoffError` before the page-table transfer
        binds a slot (nothing half-mapped — the staged context survives
        for the server to release). The server must fall back to coupled
        prefill on the decode engine for the affected request; streams
        stay bit-identical and ``tokens_lost == 0``."""
        end = None if times is None else at + times
        self._handoff_windows.append((at, end))
        return self

    def on_handoff(self, attempt: int) -> None:
        """Called by the disaggregation server with the 0-based handoff
        attempt index before ``admit_staged`` runs."""
        if self._hit(self._handoff_windows, attempt):
            self.counters["handoff_failures"] += 1
            raise InjectedHandoffError(
                f"injected handoff failure at attempt {attempt}"
            )

    def fail_spill(self, at: int = 0,
                   times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th..(at+times-1)-th KV SPILL attempts (ISSUE 19)
        raise :class:`InjectedSpillError` before the device->host pull —
        nothing leaves the pool, the entry's pins are intact. The engine
        must degrade to plain eviction: pins released, pages freed,
        ``check()`` clean, streams untouched."""
        end = None if times is None else at + times
        self._spill_windows.append((at, end))
        return self

    def fail_prefetch(self, at: int = 0,
                      times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th..(at+times-1)-th KV PREFETCH attempts (ISSUE 19)
        raise :class:`InjectedPrefetchError` before any device write. The
        engine must drop the host-tier entry (host pages released) and
        serve the request through a full prefill — bit-identical, zero
        tokens lost."""
        end = None if times is None else at + times
        self._prefetch_windows.append((at, end))
        return self

    def poison_host_page(self, at: int = 0,
                         times: Optional[int] = 1) -> "FaultInjector":
        """Corrupt one byte of the FIRST host page the ``at``-th..
        (at+times-1)-th prefetch attempts are about to fetch (ISSUE 19) —
        host-RAM bit rot, injected through the store's own ``corrupt``.
        The fingerprint verification must reject the whole fetch and the
        engine must fall back to a full prefill: corrupted host bytes
        never reach the pool."""
        end = None if times is None else at + times
        self._host_page_windows.append((at, end))
        return self

    def flip_bits(self, target: str, at: int = 0,
                  times: Optional[int] = 1) -> "FaultInjector":
        """Schedule single-bit SILENT corruption (ISSUE 20). ``target`` is
        ``params`` (flip one low-order bit of the engine's bound weights
        at the ``at``-th..(at+times-1)-th ``step()`` calls — the
        router-probe/fence path's model) or ``kv_pool`` (flip one bit of
        the first pool page the ``at``-th prefix reuse attempts map,
        BEFORE validation — the per-page fingerprint check's model)."""
        end = None if times is None else at + times
        if target == "params":
            self._params_flip_windows.append((at, end))
        elif target == "kv_pool":
            self._pool_flip_windows.append((at, end))
        else:
            raise ValueError(
                f"flip_bits target must be params|kv_pool, got {target!r}"
            )
        return self

    def on_spill(self, attempt: int) -> None:
        """Called by TIERED engines with the 0-based spill attempt index
        before the device->host pull."""
        if self._hit(self._spill_windows, attempt):
            self.counters["spill_failures"] += 1
            raise InjectedSpillError(
                f"injected spill failure at attempt {attempt}"
            )

    def on_prefetch(self, attempt: int, store=None, host_ids=()) -> None:
        """Called with the 0-based prefetch attempt index, the host store
        and the host ids about to be fetched, BEFORE verification. A
        scheduled ``poison_host_page`` corrupts the first page in place
        (the fingerprint check downstream must catch it); a scheduled
        ``fail_prefetch`` raises."""
        if self._hit(self._host_page_windows, attempt):
            if store is not None and host_ids:
                store.corrupt(host_ids[0])
                self.counters["poisoned_host_pages"] += 1
        if self._hit(self._prefetch_windows, attempt):
            self.counters["prefetch_failures"] += 1
            raise InjectedPrefetchError(
                f"injected prefetch failure at attempt {attempt}"
            )

    def drop_send(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th..(at+times-1)-th transport delivery attempts are
        dropped in flight (``TransportError`` before the target runs —
        nothing delivered, the sender's retry delivers fresh)."""
        end = None if times is None else at + times
        self._send_drops.append((at, end))
        return self

    def drop_ack(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th.. transport deliveries RUN at the target but their
        replies are lost — the sender retries a message that already
        executed, which MUST land in the transport's idempotency cache.
        This is the schedule that proves exactly-once admission."""
        end = None if times is None else at + times
        self._ack_drops.append((at, end))
        return self

    def dup_send(self, at: int = 0, times: Optional[int] = 1) -> "FaultInjector":
        """The ``at``-th.. transport deliveries arrive TWICE; the second
        copy must be absorbed by the idempotency cache, never the app."""
        end = None if times is None else at + times
        self._send_dups.append((at, end))
        return self

    def delay_send(self, at: int = 0, times: Optional[int] = 1,
                   by: float = 1.0) -> "FaultInjector":
        """The ``at``-th.. transport deliveries are delayed ``by`` seconds
        — a delay past the message's deadline becomes a terminal
        ``TransportTimeout`` (probes use this to go unanswered)."""
        end = None if times is None else at + times
        self._send_delays.append((at, end, by))
        return self

    def partition(self, target, at: int = 0,
                  times: Optional[int] = None) -> "FaultInjector":
        """Make ``target`` (a replica index or disagg address) unreachable
        for the ``at``-th.. delivery attempts — every send in the window
        fails with ``PartitionedError``. ``times=None`` partitions forever:
        the way to drive a live replica watchdog-DEAD."""
        end = None if times is None else at + times
        self._partitions.setdefault(target, []).append((at, end))
        return self

    def on_transport_send(self, send: int, target, op: str):
        """Called by ``ChaosTransport`` with the transport-wide 0-based
        delivery-attempt index, the target address and the op name.
        Returns a fault action tuple — ``("partition",)``, ``("drop",)``,
        ``("drop_ack",)``, ``("dup",)``, ``("delay", by)`` — or ``None``
        for a clean delivery. Partition wins over per-send faults (an
        unreachable target can't also deliver)."""
        windows = self._partitions.get(target)
        if windows is not None and self._hit(windows, send):
            self.counters["partitioned_sends"] += 1
            return ("partition",)
        if self._hit(self._send_drops, send):
            self.counters["dropped_sends"] += 1
            return ("drop",)
        if self._hit(self._ack_drops, send):
            self.counters["dropped_acks"] += 1
            return ("drop_ack",)
        if self._hit(self._send_dups, send):
            self.counters["dup_sends"] += 1
            return ("dup",)
        for at, end, by in self._send_delays:
            if send >= at and (end is None or send < end):
                self.counters["delayed_sends"] += 1
                return ("delay", by)
        return None

    def skew_clock(self, by: float, after: Optional[float] = None) -> "FaultInjector":
        self._skew = by
        self._skew_after = after
        return self

    # --- engine hooks --------------------------------------------------------

    @staticmethod
    def _hit(windows, index: int) -> bool:
        return any(
            index >= at and (end is None or index < end)
            for at, end in windows
        )

    def on_dispatch(self, attempt: int) -> None:
        """Called with the 0-based dispatch ATTEMPT index (failed attempts
        count, so a retry schedule is deterministic). Raises when the
        schedule says this attempt fails."""
        if self._hit(self._dispatch_windows, attempt):
            self.counters["dispatch_failures"] += 1
            raise InjectedDispatchError(
                f"injected dispatch failure at attempt {attempt}"
            )

    def on_readback(self, readback: int, toks, counts, active=None):
        """Called with the 0-based successful-readback index, the HOST
        copies of the chunk's token block ``(chunk, slots)`` and per-slot
        counts, and the active-slot mask. Returns the (possibly poisoned)
        pair. A poison whose slot is not active yet DEFERS to the next
        readback instead of firing into the void — the counter increments
        only when garbage actually lands where the engine must catch it,
        so asserting on it really proves the quarantine path ran."""
        deferred = []
        for slot, token in self._poisons.pop(readback, ()):
            if active is not None and not bool(active[slot]):
                deferred.append((slot, token))
                continue
            toks = toks.copy()
            counts = counts.copy()
            if counts[slot] <= 0:
                counts[slot] = 1  # a poisoned slot claims at least one token
            toks[0, slot] = token
            self.counters["poisoned_readbacks"] += 1
        if deferred:
            self._poisons.setdefault(readback + 1, []).extend(deferred)
        return toks, counts

    def on_spec_dispatch(self, attempt: int) -> None:
        """Called with the 0-based dispatch ATTEMPT index before a
        SPECULATIVE chunk dispatch (shares the attempt counter with
        ``on_dispatch``, so mixed schedules stay deterministic)."""
        if self._hit(self._draft_dispatch_windows, attempt):
            self.counters["draft_dispatch_failures"] += 1
            raise InjectedDraftError(
                f"injected draft dispatch failure at attempt {attempt}"
            )

    def on_spec_params(self, attempt: int, draft_params):
        """Called with the dispatch attempt index and the draft param
        pytree the speculative chunk is about to receive. When the poison
        schedule hits, returns a CORRUPTED COPY (every float leaf
        perturbed) — proposals become garbage and every round all-rejects;
        otherwise returns the tree untouched."""
        if not self._hit(self._draft_poison_windows, attempt):
            return draft_params
        import jax
        import jax.numpy as jnp

        def corrupt(leaf):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                leaf.dtype, jnp.floating
            ):
                return -leaf + jnp.asarray(3.7, leaf.dtype)
            return leaf

        self.counters["poisoned_drafts"] += 1
        return jax.tree_util.tree_map(corrupt, draft_params)

    def on_spec_readback(self, readback: int, toks, counts, active=None):
        """Speculative edition of :meth:`on_readback`: the token block is
        ``(rounds, slots, gamma)`` and counts ``(rounds, slots)``. A
        scheduled poison lands in the victim slot's FIRST round (same
        defer-until-active contract)."""
        deferred = []
        for slot, token in self._poisons.pop(readback, ()):
            if active is not None and not bool(active[slot]):
                deferred.append((slot, token))
                continue
            toks = toks.copy()
            counts = counts.copy()
            if counts[0, slot] <= 0:
                counts[0, slot] = 1
            toks[0, slot, 0] = token
            self.counters["poisoned_readbacks"] += 1
        if deferred:
            self._poisons.setdefault(readback + 1, []).extend(deferred)
        return toks, counts

    def on_page_readback(self, readback: int, slot_pages, active=None):
        """Called by PAGED engines with the 0-based successful-readback
        index and ``slot_pages`` — a callable mapping a slot index to the
        pool page ids its block table maps. Returns the page ids the
        schedule poisons at this readback. A scheduled slot that is not
        active (or maps nothing) DEFERS to the next readback — the counter
        increments only when a real page is actually poisoned, so chaos
        tests asserting on it prove the quarantine path ran."""
        pages: List[int] = []
        deferred: List[int] = []
        for slot in self._page_poisons.pop(readback, ()):
            mapped = (
                slot_pages(slot)
                if active is None or bool(active[slot]) else []
            )
            if not mapped:
                deferred.append(slot)
                continue
            pages.append(int(mapped[0]))
            self.counters["poisoned_pages"] += 1
        if deferred:
            self._page_poisons.setdefault(readback + 1, []).extend(deferred)
        return pages

    def on_prefill(self, call: int) -> None:
        """Called with the 0-based prefill call index before the prefill
        dispatch."""
        if self._hit(self._prefill_windows, call):
            self.counters["prefill_failures"] += 1
            raise InjectedPrefillError(
                f"injected prefill failure at call {call} "
                "(RESOURCE_EXHAUSTED: out of memory)"
            )

    def on_engine_params(self, step: int, params):
        """Called with the 0-based engine ``step()`` index and the bound
        (sharded) params pytree. When a ``flip_bits("params")`` window
        hits, returns the tree with ONE low-order bit of its first leaf
        flipped on every device copy — numerically near-invisible SDC
        only a bit-level fingerprint probe can see; otherwise returns the
        tree untouched."""
        if not self._hit(self._params_flip_windows, step):
            return params
        from neuronx_distributed_tpu.integrity.chaos import flip_tree_bit

        self.counters["bit_flips"] += 1
        return flip_tree_bit(params)

    def on_prefix_reuse(self, reuse: int, entry, cache=None) -> None:
        """Called with the 0-based prefix REUSE-attempt index, the matched
        ``PrefixEntry`` the engine is about to reuse, and (paged engines)
        the cache manager — BEFORE validation. A scheduled
        ``poison_prefix`` corrupts a dense entry's stored KV block IN
        PLACE (every float leaf perturbed, shapes untouched) — so the
        test proves the engine's checksum validation catches silent data
        corruption, not a shape mismatch. A scheduled
        ``flip_bits("kv_pool")`` instead flips ONE bit inside the first
        pool page a PAGED entry maps — the per-page fingerprint check
        must reject the reuse."""
        if (
            cache is not None
            and getattr(entry, "page_ids", None)
            and self._hit(self._pool_flip_windows, reuse)
        ):
            self._flip_pool_page(cache, int(entry.page_ids[0]))
            self.counters["bit_flips"] += 1
        if not self._hit(self._prefix_windows, reuse):
            return
        if getattr(entry, "tree", None) is None:
            # paged CoW entry: no host-managed KV copy to corrupt — page
            # corruption is poison_page's / flip_bits("kv_pool")'s
            # territory
            return
        import jax
        import jax.numpy as jnp

        def corrupt(leaf):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                return leaf + jnp.asarray(1024.0, leaf.dtype)
            return leaf

        entry.tree = jax.tree_util.tree_map(corrupt, entry.tree)
        self.counters["poisoned_prefixes"] += 1

    @staticmethod
    def _flip_pool_page(cache, pid: int) -> None:
        """Flip one bit of pool page ``pid``'s content in the first
        page-carrying pool leaf (host round-trip, re-placed with the
        original sharding — HBM bit rot, modeled from the host)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from neuronx_distributed_tpu.integrity.chaos import flip_array_bit
        from neuronx_distributed_tpu.modules.attention import (
            cache_leaf_name,
            pool_scale_base,
        )

        pool = cache.cache["pool"]
        flat, treedef = jax.tree_util.tree_flatten_with_path(pool)
        leaves = [leaf for _, leaf in flat]
        for i, (path, leaf) in enumerate(flat):
            name = cache_leaf_name(path)
            if (pool_scale_base(name) or name) not in ("k", "v"):
                continue
            pax = leaf.ndim - 4
            host = np.array(jax.device_get(leaf))
            idx = (slice(None),) * pax + (pid,)
            host[idx] = flip_array_bit(host[idx])
            # jnp.copy forces an XLA-owned buffer: device_put of host
            # numpy can be zero-copy on CPU backends, and the pool is
            # about to be donated by the decode dispatch (see
            # integrity/chaos.flip_leaf_bit for the full story)
            leaves[i] = jnp.copy(jax.device_put(host, leaf.sharding))
            break
        cache.cache = dict(
            cache.cache,
            pool=jax.tree_util.tree_unflatten(treedef, leaves),
        )

    def now(self, real_now: float) -> float:
        """Clock hook: the engine's view of time, skewed per schedule."""
        if self._skew and (
            self._skew_after is None or real_now >= self._skew_after
        ):
            return real_now + self._skew
        return real_now

"""Request lifecycle + admission policy for the serving engine.

Admission is FIFO with a token-budget guard: the queue is scanned in arrival
order and stops at the first request that does not fit (no overtaking — a
large request at the head cannot starve behind a stream of small ones). The
requests selected in one round are handed back LONGEST-PREFILL-FIRST: the
longest prompt sets the shared cache cursor, so prefilling it first lets the
shorter prompts roll in under the same cursor without gap columns, and its
(slowest) prefill compile/run overlaps the least work.

The token budget (``max_tokens_in_flight``) bounds Σ over in-flight requests
of (context + remaining new tokens) — the engine's worst-case claim on cache
columns — so a burst of long-generation requests queues instead of thrashing
the preemption path.
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

import numpy as np

from neuronx_distributed_tpu.inference.generate import GenerationConfig


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    DONE = "done"
    CANCELLED = "cancelled"
    # terminal fault states (serving robustness layer): TIMED_OUT — shed for
    # missing its queue timeout or in-flight deadline; FAILED — the engine
    # gave up on it for cause (``Request.error`` carries the reason, e.g. a
    # prefill fault or a quarantined slot under the fail policy)
    TIMED_OUT = "timed_out"
    FAILED = "failed"


@dataclasses.dataclass
class Request:
    """One generation request flowing through the engine.

    ``tokens`` accumulates generated ids as they stream out (the last entry
    is the pending decode input, not yet fed to the model). ``key`` is the
    request's CURRENT sampling key — it advances exactly like `generate`'s
    carry key, so a request's token stream is identical to a solo
    ``generate(prompt, key0)`` call with its original key."""

    rid: int
    prompt: np.ndarray  # (S,) int32
    config: GenerationConfig
    key: Any  # jax PRNG key data, advances as tokens are sampled
    # attribution (ISSUE 11): which tenant submitted this and at what
    # priority class — pure host strings threaded into per-tenant metrics,
    # SLO accounting, trace flows, and flight-recorder events. Scheduling
    # itself stays FIFO in this PR; the SLO-aware scheduler consumes these
    tenant: str = "default"
    priority: str = "standard"
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    preemptions: int = 0
    error: Optional[str] = None
    # absolute engine-clock deadlines (None = unbounded): ``deadline`` ends
    # the request wherever it is (enforced at chunk boundaries once
    # decoding); ``queue_deadline`` sheds it if it has not been admitted yet
    deadline: Optional[float] = None
    queue_deadline: Optional[float] = None
    # timestamps (engine clock) for metrics
    submit_time: Optional[float] = None
    admit_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def context_ids(self) -> np.ndarray:
        """Tokens already FED to the model (prompt + all generated but the
        pending last one) — what a resume-after-preemption must prefill."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens[:-1], np.int32)]
        )

    @property
    def remaining_new_tokens(self) -> int:
        return self.config.max_new_tokens - len(self.tokens)

    @property
    def token_footprint(self) -> int:
        """Worst-case cache-column claim: full context + all tokens still
        to generate (the budget-guard unit)."""
        return len(self.prompt) + len(self.tokens) + self.remaining_new_tokens

    @property
    def finished(self) -> bool:
        return self.state in (
            RequestState.DONE,
            RequestState.CANCELLED,
            RequestState.TIMED_OUT,
            RequestState.FAILED,
        )


class Scheduler:
    """Request bookkeeping + the policy seam (ISSUE 16). Queue ORDER and
    admission selection belong to the bound
    :class:`~neuronx_distributed_tpu.serving.sched.SchedulingPolicy`
    (default :class:`~neuronx_distributed_tpu.serving.sched.FifoPolicy` —
    FIFO + longest-prefill-first with the token-budget guard, verbatim
    the pre-policy behavior); intake, cancellation, deadline expiry, and
    the request index stay here."""

    def __init__(self, max_tokens_in_flight: Optional[int] = None,
                 policy=None):
        from neuronx_distributed_tpu.serving.sched import make_policy

        self.max_tokens_in_flight = max_tokens_in_flight
        self.policy = make_policy(policy)
        self._queue: Deque[Request] = deque()
        self._requests: Dict[int, Request] = {}
        # monotone flag: set once any deadline-carrying request enters the
        # queue, so deadline-free engines pay O(1) in expire() per step
        # instead of an O(queue) scan of a deep backlog
        self._saw_deadlines = False

    # --- intake -------------------------------------------------------------

    def submit(self, request: Request) -> None:
        request.state = RequestState.QUEUED
        self._requests[request.rid] = request
        self._queue.append(request)
        if request.deadline is not None or request.queue_deadline is not None:
            self._saw_deadlines = True

    def requeue_front(self, requests: List[Request]) -> None:
        """Preempted requests rejoin at the FRONT, original arrival order
        preserved — they were admitted first, they resume first."""
        for req in sorted(requests, key=lambda r: r.rid, reverse=True):
            req.state = RequestState.QUEUED
            self._queue.appendleft(req)

    def cancel(self, rid: int) -> bool:
        """Mark a request cancelled. Queued requests drop immediately;
        running ones are reaped by the engine at its next step."""
        req = self._requests.get(rid)
        if req is None or req.finished:
            return False
        req.state = RequestState.CANCELLED
        try:
            self._queue.remove(req)
        except ValueError:
            pass  # already admitted; the engine frees its slot
        return True

    def release(self, rid: int) -> Optional[Request]:
        """Withdraw a QUEUED request from this scheduler entirely — queue
        AND index — and return it live (state untouched) so ANOTHER engine
        can adopt it (router rebalancing / surrendered warm-restart state).
        Unlike :meth:`cancel` the request is not finished, and unlike a
        halt handoff this scheduler forgets the rid, so a later restore
        can never double-admit it here. Returns ``None`` when the rid is
        unknown, finished, or not currently queued."""
        req = self._requests.get(rid)
        if req is None or req.finished or req.state is not RequestState.QUEUED:
            return None
        try:
            self._queue.remove(req)
        except ValueError:
            return None  # admitted into a slot — not releasable
        del self._requests[rid]
        return req

    def expire(self, now: float) -> List[tuple]:
        """Pop every still-queued request whose queue timeout or overall
        deadline has passed (``now >= deadline``) and return ``(request,
        reason)`` pairs — the engine marks them TIMED_OUT and records the
        shed with the reason (classified HERE, the one place the overdue
        predicate lives). The queue timeout governs time-to-FIRST-admission
        only: a request requeued after preemption or dispatch recovery was
        already admitted in time (``admit_time`` set), so only its overall
        deadline can still shed it. Queue order of the survivors is
        preserved; a queue that never saw a deadline returns in O(1)."""
        expired: List[tuple] = []
        if not self._queue or not self._saw_deadlines:
            return expired
        keep: Deque[Request] = deque()
        while self._queue:
            req = self._queue.popleft()
            if req.state is not RequestState.QUEUED:
                keep.append(req)
            elif (
                req.queue_deadline is not None
                and req.admit_time is None
                and now >= req.queue_deadline
            ):
                expired.append((req, "queue timeout before admission"))
            elif req.deadline is not None and now >= req.deadline:
                expired.append((req, "deadline exceeded while queued"))
            else:
                keep.append(req)
        self._queue = keep
        return expired

    # --- admission ----------------------------------------------------------

    def select(
        self,
        free_slots: int,
        in_flight_tokens: int,
        fits: Optional[Callable[[Request], bool]] = None,
        prefill_cost: Optional[Callable[[Request], int]] = None,
        now: Optional[float] = None,
    ) -> List[Request]:
        """Delegate one admission round to the bound policy: pick the
        queue-order prefix that fits ``free_slots``, the token budget, and
        the engine's capacity predicate ``fits`` (checked in queue order,
        so ``fits`` may accumulate a projected cursor). Selected requests
        leave the queue in state PREFILL, returned longest-prefill-first.

        The scan and ordering live in ONE place —
        :func:`~neuronx_distributed_tpu.serving.sched.scan_queue` /
        :func:`~neuronx_distributed_tpu.serving.sched.order_round` (they
        used to be duplicated between this method and the engine's
        admission path). ``prefill_cost`` replaces the ordering key with
        the EFFECTIVE prefill work (the prefix-cache-aware engine passes
        context length minus reusable tokens): ordering only — selection,
        capacity projection, and the cursor targets ``fits`` accumulates
        follow the policy's queue order, so FIFO token streams are
        unaffected. ``now`` feeds time-aware policies (aging, preemption
        cooldowns); the FIFO policy ignores it."""
        return self.policy.select(
            self._queue, free_slots, in_flight_tokens,
            self.max_tokens_in_flight, fits, prefill_cost, now=now,
        )

    # --- introspection ------------------------------------------------------

    @property
    def requests(self) -> Dict[int, Request]:
        """Every request this scheduler has seen, by rid."""
        return self._requests

    @property
    def queued_requests(self) -> List[Request]:
        """Live (not finished) queue entries, in queue order."""
        return [r for r in self._queue if not r.finished]

    @property
    def queued(self) -> int:
        return len(self.queued_requests)

    def upcoming(self, n: int) -> List[Request]:
        """The first ``n`` live queued requests in plain queue order —
        the tiered-KV prefetch pre-pass's peek window (ISSUE 19).
        READ-only and policy-blind on purpose: it never consults
        ``policy.select``, so it cannot perturb admission. Prefetching
        for a request the policy admits later (or never) merely moves
        pages early — correctness never depends on this ordering."""
        if n <= 0:
            return []
        out: List[Request] = []
        for r in self._queue:
            if r.finished:
                continue
            out.append(r)
            if len(out) >= n:
                break
        return out

    def queued_by_tenant(self) -> Dict[str, int]:
        """Live queue depth per tenant, tenant-sorted — who is waiting
        (and, at halt time, who was being starved: the flight recorder
        dumps this into the post-mortem's ``extra``)."""
        depths: Dict[str, int] = {}
        for r in self.queued_requests:
            depths[r.tenant] = depths.get(r.tenant, 0) + 1
        return dict(sorted(depths.items()))

    def get(self, rid: int) -> Optional[Request]:
        return self._requests.get(rid)

"""Paged KV cache for the serving engine: block-table slots over a
ref-counted page pool with zero-copy copy-on-write prefix sharing.

The row-per-slot manager (``serving/cache_manager.py``) charges every slot a
full ``max_seq_len`` row of HBM whatever its request actually uses, and the
prefix cache physically copies KV on every insert and reuse. This module
replaces that storage model at BLOCK/PAGE granularity while keeping every
byte of the decode math untouched:

* :class:`PageAllocator` owns a fixed pool of ``num_pages`` KV pages
  (``page_size`` cache columns each), free-listed and ref-counted. Page 0 is
  the reserved NULL page — never allocated, the scatter target of every
  unmapped block-table entry, never attendable.
* :class:`PagedCacheManager` is the drop-in slot manager: each slot holds a
  block table row (host-authoritative numpy mirror, uploaded as the
  ``pages`` leaf of the paged cache pytree ``{"pages": bt, "pool": tree}``
  the decode chunk donates). The jitted chunk gathers the logical
  ``(num_slots, max_seq_len)`` view through the table, runs the EXACT
  row-per-slot math (attention masking/RoPE still run off per-row
  ``kv_valid`` counts), and scatters back only its write window
  (``modules/attention.gather_cache_pages`` / ``scatter_cache_window`` over
  ``kernels/flash_decode``'s paged transport) — so token streams are
  bit-identical across layouts and XLA still compiles ONE decode program.
* Copy-on-write prefix sharing: every paged admission page-aligns its
  context start (the cursor target is bumped ``< page_size`` columns; gap
  columns stay invalid as ever), so insert-on-miss PINS the slot's
  whole context pages instead of extracting a compact copy, and a later
  hit maps those pages straight into the new slot's block table — ref-counts
  up, ZERO KV bytes copied (``PageAllocator.copy_bytes`` stays 0 by
  construction; the allocator accounting is the test surface). Decode
  writes always land beyond the aligned shared range, and the chunk's
  window scatter never rewrites pages outside the window, so shared pages
  are bit-stable while any number of holders decode off them.
* Page-granular fault domains: a poisoned page is quarantined out of the
  POOL (``PageAllocator.quarantine``) — only the requests whose tables map
  it are requeued, the slot indices stay in rotation, and capacity degrades
  by one page instead of one permanent slot row.

Every manager instance registers in a weak set; ``check_all_live()`` runs
the leak/ref-count invariant (:meth:`PagedCacheManager.check`) over all
live managers — the serving test suite calls it after every test teardown.
"""

from __future__ import annotations

import dataclasses
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from neuronx_distributed_tpu.modules.attention import (
    _SCALE_SUFFIX,
    cache_batch_axis,
    cache_leaf_name,
    cache_node_at,
    pool_scale_base,
    pool_scale_sibling,
    reset_cache,
    reset_cache_slot,
    seed_cache_prefix,
)
from neuronx_distributed_tpu.observability.programs import per_instance

_LIVE_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()


def check_all_live() -> int:
    """Run the page-leak/ref-count invariant over every live
    :class:`PagedCacheManager` (the serving suite's teardown fixture).
    Returns how many managers were checked; raises AssertionError on the
    first violated invariant."""
    n = 0
    for mgr in list(_LIVE_MANAGERS):
        mgr.check()
        n += 1
    return n


@dataclasses.dataclass
class StagedContext:
    """A prefilled context parked in the page pool with NO slot bound — the
    disaggregated prefill→decode handoff unit (ISSUE 14). ``page_ids`` hold
    the context's K/V page-aligned from the first page's column 0 (so a
    later ``map_staged`` can bind them at any page-aligned cursor — K/V
    content is position-relative, exactly why CoW prefix pages are
    remappable); the staging itself holds one pool reference per page
    until the handoff transfers it to a slot's block table (zero KV bytes
    move — ``PageAllocator.copy_bytes`` untouched) or ``release_staged``
    drops it."""

    page_ids: Tuple[int, ...]
    p: int        # real context tokens staged
    padded: int   # the prefill bucket the row was computed at


@dataclasses.dataclass
class ExportedContext:
    """Device-transfer form of a staged context for DISTINCT prefill and
    decode pools (different hosts/meshes): raw page blocks per pool k/v
    leaf (quantized pools export their scale sibling blocks too). Import
    is a REAL copy — ``PageAllocator.copy_bytes`` charges it, which is
    precisely how the shared-pool path proves it moved nothing."""

    items: list   # [(tree keys tuple, (..., n, page_size_or_1, ...) block)]
    n_pages: int
    p: int
    padded: int


class PageExhausted(RuntimeError):
    """The pool has fewer free pages than an allocation needs (after any
    reclaim callback ran dry). Admission accounting exists to make this
    unreachable on the conservative path; the eager path treats it as the
    page-pressure wall (preempt-and-rewind)."""


class PageAllocator:
    """Host-side owner of the physical page pool: free list + ref counts.

    A page is exactly one of: RESERVED (page 0, the null scatter target),
    FREE (on the free list, refcount absent), REFERENCED (mapped by >= 1
    block table and/or pinned by >= 1 prefix entry — the refcount is the
    sum), or QUARANTINED (poisoned, permanently out of circulation; a
    referenced page that gets quarantined leaves circulation when its last
    ref drops). ``copy_bytes`` counts KV bytes physically duplicated on
    prefix reuse — the zero-copy CoW contract is that it STAYS 0."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved), got {num_pages}"
            )
        self.num_pages = num_pages
        self._free: List[int] = list(range(1, num_pages))
        self._refs: Dict[int, int] = {}
        self._quarantined: set = set()
        self.copy_bytes = 0  # CoW contract: never incremented by sharing

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def referenced_pages(self) -> int:
        return len(self._refs)

    @property
    def pages_quarantined(self) -> int:
        return len(self._quarantined)

    @property
    def capacity(self) -> int:
        """Usable pages: everything but the reserved null page and the
        quarantined set (referenced or free alike)."""
        return self.num_pages - 1 - len(self._quarantined)

    def refcount(self, pid: int) -> int:
        return self._refs.get(pid, 0)

    def alloc(self, n: int) -> List[int]:
        """Take ``n`` pages off the free list, each born with refcount 1
        (the caller's mapping). Raises :class:`PageExhausted` when short."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise PageExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"(capacity {self.capacity})"
            )
        ids = [self._free.pop(0) for _ in range(n)]
        for pid in ids:
            self._refs[pid] = 1
        return ids

    def ref(self, pid: int) -> None:
        """One more holder of an already-live page (CoW share / prefix pin)."""
        if pid not in self._refs:
            raise ValueError(f"page {pid} is not live (cannot ref)")
        self._refs[pid] += 1

    def deref(self, pid: int) -> None:
        """Drop one holder; the last drop returns the page to the free list
        (or retires it for good if it was quarantined while referenced)."""
        c = self._refs.get(pid)
        if c is None:
            raise ValueError(f"page {pid} is not live (cannot deref)")
        if c > 1:
            self._refs[pid] = c - 1
            return
        del self._refs[pid]
        if pid not in self._quarantined:
            self._free.append(pid)
            self._free.sort()

    def quarantine(self, pid: int) -> None:
        """Pull a page out of circulation permanently (poisoned content).
        A free page leaves the free list now; a referenced page keeps
        serving its current holders' BOOKKEEPING (they are being requeued
        by the caller) and retires when the last ref drops."""
        if pid <= 0 or pid >= self.num_pages:
            raise ValueError(f"page {pid} outside pool [1, {self.num_pages})")
        self._quarantined.add(pid)
        if pid in self._free:
            self._free.remove(pid)

    def release_all(self) -> None:
        """Drop every reference (pool-loss recovery: all mappings and pins
        are void). Quarantined pages stay out of circulation."""
        for pid in list(self._refs):
            del self._refs[pid]
            if pid not in self._quarantined:
                self._free.append(pid)
        self._free.sort()


class PagedCacheManager:
    """Host-side owner of a paged cache collection + slot/block-table
    bookkeeping — the page-granular sibling of ``SlotCacheManager`` (same
    take/restore/recover/update_after_decode donation protocol, same shared
    write cursor semantics; the engine drives either through one code
    path). Device work is a handful of jitted programs: paged admission
    roll-in (scatter the prefill row's occupied pages through host-chosen
    ids), per-slot free / full reset (the row manager's exact programs —
    they touch only ``kv_valid``/``index`` leaves, which stay logical), and
    the non-donating seed-from-pages gather behind zero-copy prefix hits."""

    def __init__(self, num_slots: int, max_seq_len: int, page_size: int,
                 num_pages: Optional[int] = None,
                 kv_quant: Optional[str] = None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_seq_len % page_size != 0:
            raise ValueError(
                f"max_seq_len ({max_seq_len}) must be a multiple of "
                f"page_size ({page_size})"
            )
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f"unknown kv_quant {kv_quant!r} (expected 'int8' or None)"
            )
        # quantized pool (ISSUE 13): k/v pages stored int8 with per-page,
        # per-kv-head scale SIBLING leaves (k_scale/v_scale, dtype = the
        # compute dtype). The jitted transports (gather/scatter/admit/seed)
        # detect the siblings and de/re-quantize in-program; all HOST
        # accounting here (block tables, refs, pins, quarantine) is
        # layout-blind, so CoW sharing and the leak invariant are unchanged
        self.kv_quant = kv_quant
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.page_size = page_size
        self.pages_per_row = max_seq_len // page_size
        if num_pages is None:
            # default: the row manager's exact HBM (every slot a full row)
            # plus the reserved null page — paging is then a pure layout
            # change; smaller pools buy the packing win
            num_pages = num_slots * self.pages_per_row + 1
        self.alloc = PageAllocator(num_pages)
        self.cache = None  # {"pages": bt, "pool": tree}; lazy like the row mgr
        self.cursor = 0
        self._free = list(range(num_slots))
        self._quarantined: set = set()  # slot indices (API compat; rare)
        self._tables = np.zeros((num_slots, self.pages_per_row), np.int32)
        self._slot_start: List[Optional[int]] = [None] * num_slots
        self._pins: Dict[int, int] = {}  # page -> prefix-entry pin count
        # engine-installed pressure valve: evict one unpinned prefix entry,
        # return whether anything was reclaimed
        self.reclaim: Optional[Callable[[], bool]] = None
        # TP serving (ISSUE 14): placement hook applied once at pool
        # allocation (kv-head-axis sharding over the engine's mesh)
        self.placement = None
        self.prefix_pages_shared_total = 0
        ps, n_log = page_size, self.pages_per_row

        def _paged_admit(paged, row, slot, shift, cursor, ids, lo_page):
            from neuronx_distributed_tpu.kernels.flash_decode import (
                paged_write_pages_leaf,
                quantize_page_block,
            )

            n_adm = ids.shape[0]
            pool_in = paged["pool"]

            def row_pages(path, base):
                """The admitted row's n_adm page blocks for pool leaf
                ``base`` (k or v) — shared by the page write and (on a
                quantized pool) the sibling scale write, which XLA CSEs
                inside the one jitted admit program."""
                row_leaf = cache_node_at(row, path[:-1])[base]
                r_ax = row_leaf.ndim - 4  # row batch axis
                col = r_ax + 1
                rolled = jnp.roll(row_leaf, shift, axis=col)
                lead = row_leaf.shape[:r_ax]
                tail = row_leaf.shape[col + 1:]
                pg = rolled.reshape(lead + (1, n_log, ps) + tail)
                win = jax.lax.dynamic_slice_in_dim(
                    pg, lo_page, n_adm, axis=r_ax + 1
                )
                return win.reshape(lead + (n_adm, ps) + tail)

            def fn(path, pool_leaf):
                name = cache_leaf_name(path)
                base = pool_scale_base(name) or name
                if base in ("k", "v"):
                    pages = row_pages(path, base)
                    if pool_scale_sibling(pool_in, path, base) is not None:
                        q, s = quantize_page_block(pages)
                        pages = q if base == name else s
                    return paged_write_pages_leaf(pool_leaf, pages, ids)
                ax = cache_batch_axis(name, pool_leaf.ndim)
                if name == "kv_valid":
                    row_leaf = cache_node_at(row, path[:-1])[name]
                    rolled = jnp.roll(row_leaf, shift, axis=ax + 1)
                    return jax.lax.dynamic_update_slice_in_dim(
                        pool_leaf, rolled, slot, axis=ax
                    )
                return jnp.full_like(pool_leaf, cursor)

            pool = jax.tree_util.tree_map_with_path(fn, pool_in)
            return {"pages": paged["pages"], "pool": pool}

        def _seed_from_pages(pool, ids, m, start):
            """Batch-1 row whose columns [start, start+m) hold the first
            ``m`` tokens of the shared pages ``ids`` — the zero-copy twin
            of ``seed_cache_prefix`` on a stored entry COPY. The pool is
            READ (never donated, never aliased into the result): the
            gather materializes compute-only views, no pool page moves.
            Quantized pools dequantize into the compute view here (the
            suffix prefill consumes a float row either way); the scale
            siblings never reach the row."""
            from neuronx_distributed_tpu.kernels.flash_decode import (
                paged_read_pages_leaf,
                paged_read_pages_leaf_dequant,
            )
            from neuronx_distributed_tpu.utils.tree import path_keys

            bucket = ids.shape[0] * ps
            items = []
            for path, leaf in jax.tree_util.tree_flatten_with_path(pool)[0]:
                keys = tuple(path_keys(path))
                name = keys[-1]
                if pool_scale_base(name) is not None:
                    continue  # transport metadata — not part of a row
                if name in ("k", "v"):
                    scale = pool_scale_sibling(pool, path, name)
                    block = (
                        paged_read_pages_leaf_dequant(leaf, scale, ids, ps)
                        if scale is not None
                        else paged_read_pages_leaf(leaf, ids)
                    )
                    leaf = jnp.expand_dims(block, leaf.ndim - 4)
                elif name == "kv_valid":
                    ax = cache_batch_axis(name, leaf.ndim)
                    valid = jnp.arange(bucket, dtype=jnp.int32)[None] < m
                    leaf = jnp.broadcast_to(
                        valid, leaf.shape[:ax] + (1, bucket)
                    )
                else:
                    leaf = jnp.full_like(leaf, m)
                items.append((keys, leaf))
            from neuronx_distributed_tpu.modules.attention import (
                _rebuild_tree,
            )

            return seed_cache_prefix(
                _rebuild_tree(items), m, start, max_seq_len
            )

        def _stage_context(paged, row, shift, ids):
            """Disaggregated handoff, write half (ISSUE 14): scatter a
            prefill row's context pages into the pool at host-chosen ids —
            rolled so the context's first token sits at the first page's
            column 0 (position-relative K/V makes the block mappable at
            any aligned cursor later). kv_valid/index are untouched: slot
            binding is ``_map_slot_context``'s, at handoff time."""
            from neuronx_distributed_tpu.kernels.flash_decode import (
                paged_write_pages_leaf,
                quantize_page_block,
            )

            n_st = ids.shape[0]
            pool_in = paged["pool"]

            def fn(path, pool_leaf):
                name = cache_leaf_name(path)
                base = pool_scale_base(name) or name
                if base not in ("k", "v"):
                    return pool_leaf
                row_leaf = cache_node_at(row, path[:-1])[base]
                r_ax = row_leaf.ndim - 4
                col = r_ax + 1
                rolled = jnp.roll(row_leaf, shift, axis=col)
                lead = row_leaf.shape[:r_ax]
                tail = row_leaf.shape[col + 1:]
                pg = rolled.reshape(lead + (1, n_log, ps) + tail)
                win = jax.lax.dynamic_slice_in_dim(
                    pg, 0, n_st, axis=r_ax + 1
                )
                pages = win.reshape(lead + (n_st, ps) + tail)
                if pool_scale_sibling(pool_in, path, base) is not None:
                    q, s = quantize_page_block(pages)
                    pages = q if base == name else s
                return paged_write_pages_leaf(pool_leaf, pages, ids)

            return {
                "pages": paged["pages"],
                "pool": jax.tree_util.tree_map_with_path(fn, pool_in),
            }

        def _map_slot_context(paged, slot, start, p, cursor):
            """Disaggregated handoff, bind half: the METADATA-only program
            — set the slot's kv_valid over its context columns and the
            shared cursor. No K/V byte moves; the block-table row (host
            side) is what carries the pages."""
            def fn(path, leaf):
                name = cache_leaf_name(path)
                base = pool_scale_base(name) or name
                if base in ("k", "v"):
                    return leaf
                ax = cache_batch_axis(name, leaf.ndim)
                if name == "kv_valid":
                    length = leaf.shape[-1]
                    cols = jnp.arange(length, dtype=jnp.int32)
                    rowv = (cols >= start) & (cols < start + p)
                    rowv = jnp.broadcast_to(
                        rowv, leaf.shape[:ax] + (1, length)
                    )
                    return jax.lax.dynamic_update_slice_in_dim(
                        leaf, rowv, slot, axis=ax
                    )
                return jnp.full_like(leaf, cursor)

            return {
                "pages": paged["pages"],
                "pool": jax.tree_util.tree_map_with_path(
                    fn, paged["pool"]
                ),
            }

        def _import_blocks(paged, blocks, ids):
            """Distinct-pool handoff fallback: write exported page blocks
            (k/v and any scale siblings, already in pool storage form)
            into this pool at ``ids`` — the explicit device transfer the
            shared-pool path never pays."""
            from neuronx_distributed_tpu.kernels.flash_decode import (
                paged_write_pages_leaf,
            )

            def fn(path, pool_leaf):
                name = cache_leaf_name(path)
                base = pool_scale_base(name) or name
                if base not in ("k", "v"):
                    return pool_leaf
                block = cache_node_at(blocks, path[:-1])[name]
                return paged_write_pages_leaf(pool_leaf, block, ids)

            return {
                "pages": paged["pages"],
                "pool": jax.tree_util.tree_map_with_path(
                    fn, paged["pool"]
                ),
            }

        # _paged_admit/_seed_from_pages are per-manager closures already;
        # the module-level reset helpers need per_instance for the same
        # pjit-cache-per-function-object reason as SlotCacheManager
        self._admit_fn = jax.jit(_paged_admit, donate_argnums=(0,))
        self._seed_fn = jax.jit(_seed_from_pages)
        self._free_fn = jax.jit(per_instance(reset_cache_slot), donate_argnums=(0,))
        self._reset_fn = jax.jit(per_instance(reset_cache), donate_argnums=(0,))
        self._stage_fn = jax.jit(_stage_context, donate_argnums=(0,))
        self._map_fn = jax.jit(_map_slot_context, donate_argnums=(0,))
        self._import_fn = jax.jit(_import_blocks, donate_argnums=(0,))
        # page -> outstanding staged-context holds (disaggregated handoff);
        # counted into the leak invariant like pins
        self._staged: Dict[int, int] = {}
        # tiered KV (ISSUE 19): pages written back by a host-tier prefetch
        # for a QUEUED request — pinned (the pin IS their reference; check()
        # reconciles them there) but additionally marked so the admission
        # fit math and the reclaim valve treat them as claimed, not
        # reclaimable: evicting a page the very next admission round is
        # about to map would be pure churn. Holds clear when the entry is
        # consumed or evicted (engine-side) and are VOID on pool recovery
        self._prefetch_hold: set = set()
        _LIVE_MANAGERS.add(self)

    def register_programs(self, programs, prefix: str = "") -> None:
        """Wrap the manager's jitted programs in a
        :class:`~neuronx_distributed_tpu.observability.programs.
        ProgramLedger` (ISSUE 12); proxies forward ``_cache_size()`` so
        ``seed_compilations`` keeps reading through."""
        self._admit_fn = programs.wrap(f"{prefix}paged_admit", self._admit_fn)
        self._seed_fn = programs.wrap(f"{prefix}paged_seed", self._seed_fn)
        self._free_fn = programs.wrap(f"{prefix}paged_free", self._free_fn)
        self._reset_fn = programs.wrap(f"{prefix}paged_reset", self._reset_fn)
        self._stage_fn = programs.wrap(f"{prefix}paged_stage", self._stage_fn)
        self._map_fn = programs.wrap(f"{prefix}paged_map", self._map_fn)
        self._import_fn = programs.wrap(
            f"{prefix}paged_import", self._import_fn
        )

    # --- HBM accounting ----------------------------------------------------

    @property
    def nbytes(self) -> int:
        """Bytes of the live pool + block tables (leaf metadata — no sync;
        0 before first allocation or while a donating consumer holds it)."""
        from neuronx_distributed_tpu.observability.hbm import tree_nbytes

        return tree_nbytes(self.cache) if self.cache is not None else 0

    @property
    def page_nbytes(self) -> int:
        """Bytes one pool page occupies across the k/v leaves — the HBM
        ledger's ``plan()`` unit for paged capacity questions."""
        if self.cache is None:
            return 0
        total = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            self.cache["pool"]
        )[0]:
            name = cache_leaf_name(path)
            # pool k/v leaves are (..., P, page_size, Hkv, D), their
            # quantized scale siblings (..., P, 1, Hkv, 1) — the page axis
            # sits 4 from the end either way (leading axes are nn.scan
            # layer stacking); scales are real per-page HBM, so plan()
            # capacity math must charge them
            if name in ("k", "v") or pool_scale_base(name) is not None:
                pages_ax = max(int(leaf.shape[leaf.ndim - 4]), 1)
                total += int(leaf.nbytes) // pages_ax
        return total

    # --- slot accounting (SlotCacheManager surface) -------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free) - len(self._quarantined)

    @property
    def usable_slots(self) -> int:
        return self.num_slots - len(self._quarantined)

    @property
    def quarantined_slots(self) -> list:
        return sorted(self._quarantined)

    @property
    def pages_quarantined(self) -> int:
        return self.alloc.pages_quarantined

    @property
    def degraded(self) -> bool:
        """Capacity permanently shrunk (poisoned pages retired)."""
        return self.alloc.pages_quarantined > 0

    @property
    def pages_mapped(self) -> int:
        return int((self._tables != 0).sum())

    @property
    def seed_compilations(self) -> int:
        """Distinct zero-copy seed programs compiled (one per shared page
        count) — bounded by ``pages_per_row``, never by hit traffic."""
        return int(self._seed_fn._cache_size())

    def acquire(self) -> int:
        return self._free.pop(0)

    def quarantine(self, slot: int) -> None:
        """Slot-poison entry point (the engine's poisoned-readback path):
        page-granular — the slot's EXCLUSIVELY-owned pages are retired from
        the pool (their content is suspect), shared/pinned pages predate
        this slot and survive, and the slot index itself stays in rotation
        (the caller's ``free`` returns it). Capacity degrades by the pages
        lost, not a permanent row."""
        row = self._tables[slot]
        for pid in {int(p) for p in row[row != 0]}:
            if self.alloc.refcount(pid) == 1 and self._pins.get(pid, 0) == 0:
                self.alloc.quarantine(pid)

    def quarantine_page(self, pid: int) -> List[int]:
        """Retire one poisoned page from the pool; returns the slots whose
        block tables currently map it (the caller requeues exactly those —
        the page-granular fault domain)."""
        self.alloc.quarantine(pid)
        return [
            s for s in range(self.num_slots)
            if (self._tables[s] == pid).any()
        ]

    # --- page math ----------------------------------------------------------

    def aligned_target(self, base: int, p: int) -> int:
        """Smallest cursor >= ``base`` placing a p-token context's first
        token on a page boundary (``(target - p) % page_size == 0``) — the
        alignment every paged admission enforces so whole context pages are
        shareable. Costs < page_size gap columns, invisible to the math."""
        return base + (-(base - p)) % self.page_size

    def page_span(self, lo_col: int, hi_col: int) -> int:
        """Pages overlapped by columns [lo_col, hi_col)."""
        hi_col = min(hi_col, self.max_seq_len)
        if hi_col <= lo_col:
            return 0
        return -(-hi_col // self.page_size) - lo_col // self.page_size

    def active_spans(self) -> List[int]:
        """Start column of every slot currently holding a context (the
        admission projection's per-slot page-span inputs)."""
        return [s for s in self._slot_start if s is not None]

    def reclaimable_pages(self) -> int:
        """Pages an eviction (or spill-to-host) could actually free RIGHT
        NOW: pinned by prefix entries, mapped by no slot, un-quarantined —
        and not held by an in-flight prefetch (those are the opposite of
        reclaimable: a queued request is about to map them). Feeds both
        the eager-admission budget and the router's reclaimable-via-spill
        capacity term."""
        return sum(
            1 for pid, pins in self._pins.items()
            if pins > 0
            and self.alloc.refcount(pid) == pins
            and pid not in self.alloc._quarantined
            and pid not in self._prefetch_hold
        )

    def available_pages(self) -> int:
        """Free pages plus what evicting every unpinned-by-flight prefix
        entry could reclaim (pages pinned by entries and mapped by no
        slot) — the eager-admission page budget. In-flight prefetch holds
        are excluded on BOTH sides: held pages are neither free nor
        reclaimable, so the fit math counts them as claimed (ISSUE 19)."""
        return self.alloc.free_pages + self.reclaimable_pages()

    def _alloc_pages(self, n: int) -> List[int]:
        while self.alloc.free_pages < n and self.reclaim is not None:
            if not self.reclaim():
                break
        return self.alloc.alloc(n)

    # --- prefix pins (CoW) --------------------------------------------------

    def pin_pages(self, ids: Sequence[int]) -> None:
        """A prefix entry takes a reference on each page (insert-on-miss:
        the slot's own context pages become shared storage, zero copies)."""
        for pid in ids:
            self.alloc.ref(int(pid))
            self._pins[int(pid)] = self._pins.get(int(pid), 0) + 1

    def unpin_pages(self, ids: Sequence[int]) -> None:
        for pid in ids:
            pid = int(pid)
            pins = self._pins.get(pid, 0)
            if pins <= 1:
                self._pins.pop(pid, None)
            else:
                self._pins[pid] = pins - 1
            self.alloc.deref(pid)

    def hold_prefetched(self, ids: Sequence[int]) -> None:
        """Mark freshly prefetched (already pinned) pages as claimed by a
        queued request — excluded from the reclaimable sum until released
        (consumption or entry eviction)."""
        self._prefetch_hold.update(int(pid) for pid in ids)

    def release_prefetched(self, ids: Sequence[int]) -> None:
        """Drop prefetch holds (no-op for pages that carry none)."""
        self._prefetch_hold.difference_update(int(pid) for pid in ids)

    def prefetch_held(self, ids: Sequence[int]) -> bool:
        """Whether ANY of ``ids`` is claimed by an in-flight prefetch —
        the reclaim valve skips entries whose pages are (evict-then-refetch
        churn would waste the transfer the prefetch just paid for)."""
        return any(int(pid) in self._prefetch_hold for pid in ids)

    def pages_live(self, ids: Sequence[int]) -> bool:
        """Reuse-time validation for a paged prefix entry: every page still
        allocated, pinned, and un-quarantined (host accounting only — the
        content never left the pool, so there is nothing to checksum)."""
        return all(
            self.alloc.refcount(int(pid)) > 0
            and self._pins.get(int(pid), 0) > 0
            and int(pid) not in self.alloc._quarantined
            for pid in ids
        )

    def slot_context_pages(self, slot: int, n: int) -> List[int]:
        """The first ``n`` context pages of a slot (insert-on-miss pins
        exactly these)."""
        start = self._slot_start[slot]
        if start is None:
            raise ValueError(f"slot {slot} holds no context")
        lo = start // self.page_size
        ids = [int(p) for p in self._tables[slot, lo:lo + n]]
        if any(p == 0 for p in ids):
            raise ValueError(
                f"slot {slot} pages {ids} include unmapped entries"
            )
        return ids

    def slot_pages(self, slot: int) -> List[int]:
        row = self._tables[slot]
        return [int(p) for p in row[row != 0]]

    # --- device-state transitions -------------------------------------------

    def _upload_tables(self) -> None:
        if self.cache is not None:
            pages = jnp.asarray(self._tables)
            if self.placement is not None:
                # keep the uploaded table committed-replicated like the
                # allocation-time one — a layout flip between chunks would
                # recompile the decode program (decode_compilations pin)
                pages = self.placement({"pages": pages})["pages"]
            self.cache = dict(self.cache, pages=pages)

    def allocate_from(self, row_cache) -> None:
        """Build the page pool + block table from a batch-1 prefill row's
        structure — zeros everywhere; happens exactly once (lazily). With
        ``kv_quant`` the k/v pool leaves are int8 and each gains a
        per-page, per-kv-head scale SIBLING (``k_scale``/``v_scale``,
        dtype = the row's compute dtype — the transport dequantizes into
        it), so HBM holds ~1-byte KV: ~2x (bf16) / ~4x (fp32) pages at a
        fixed budget on top of paging's packing."""
        from neuronx_distributed_tpu.modules.attention import _rebuild_tree
        from neuronx_distributed_tpu.utils.tree import path_keys

        num_pages, ps = self.alloc.num_pages, self.page_size
        items = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(row_cache)[0]:
            keys = tuple(path_keys(path))
            name = keys[-1]
            ax = cache_batch_axis(name, leaf.ndim)
            if name in ("k", "v"):
                lead = leaf.shape[:ax]
                tail = leaf.shape[ax + 2:]  # (Hkv, D)
                if self.kv_quant is not None:
                    items.append(
                        (keys, jnp.zeros(lead + (num_pages, ps) + tail,
                                         jnp.int8))
                    )
                    items.append((
                        keys[:-1] + (name + _SCALE_SUFFIX,),
                        jnp.zeros(
                            lead + (num_pages, 1) + tail[:-1] + (1,),
                            leaf.dtype,
                        ),
                    ))
                else:
                    items.append(
                        (keys,
                         jnp.zeros(lead + (num_pages, ps) + tail, leaf.dtype))
                    )
            elif name == "kv_valid":
                lead = leaf.shape[:ax]
                items.append(
                    (keys, jnp.zeros(
                        lead + (self.num_slots, self.max_seq_len), jnp.bool_
                    ))
                )
            else:
                items.append((keys, jnp.zeros_like(leaf)))
        self.cache = {
            "pages": jnp.asarray(self._tables),
            "pool": _rebuild_tree(items),
        }
        if self.placement is not None:
            self.cache = self.placement(self.cache)

    def allocate_like(self, other: "PagedCacheManager") -> None:
        """Build this pool from ANOTHER manager's allocated pool structure
        (own ``num_pages``/``num_slots`` geometry) — the distinct-pool
        disaggregation path's decode-side bootstrap, where the decode
        engine may never have run a prefill of its own."""
        if other.cache is None:
            raise RuntimeError("source manager has no allocated pool")
        if self.cache is not None:
            return

        def fn(path, leaf):
            name = cache_leaf_name(path)
            base = pool_scale_base(name) or name
            if base in ("k", "v"):
                pax = leaf.ndim - 4
                shape = list(leaf.shape)
                shape[pax] = self.alloc.num_pages
                return jnp.zeros(tuple(shape), leaf.dtype)
            if name == "kv_valid":
                ax = cache_batch_axis(name, leaf.ndim)
                shape = list(leaf.shape)
                shape[ax] = self.num_slots
                return jnp.zeros(tuple(shape), jnp.bool_)
            return jnp.zeros(leaf.shape, leaf.dtype)

        self.cache = {
            "pages": jnp.asarray(self._tables),
            "pool": jax.tree_util.tree_map_with_path(
                fn, other.cache["pool"]
            ),
        }
        if self.placement is not None:
            self.cache = self.placement(self.cache)

    def admit(self, row_cache, slot: int, padded_len: int,
              cursor: Optional[int] = None, p: Optional[int] = None,
              shared_ids: Sequence[int] = (), m_shared: int = 0) -> None:
        """Roll a prefill row into ``slot`` at page granularity: map
        ``shared_ids`` (a CoW prefix hit's pages, ref-counted up — zero KV
        bytes move) over the first ``m_shared`` context columns, allocate
        own pages for the rest, scatter ONLY those own pages out of the
        rolled row, and set the shared cursor to ``cursor``. ``p`` is the
        real context length (default ``padded_len``); the context start
        ``cursor - p`` must be page-aligned (``aligned_target``)."""
        p = padded_len if p is None else p
        ps, n_log = self.page_size, self.pages_per_row
        if self.cache is None:
            if self.cursor > 0:
                raise RuntimeError(
                    "cache collection missing mid-flight (cursor "
                    f"{self.cursor}): a take() was never paired with "
                    "update_after_decode/restore"
                )
            self.allocate_from(row_cache)
        target = (
            self.aligned_target(max(self.cursor, padded_len), p)
            if cursor is None else cursor
        )
        if target < padded_len:
            raise ValueError(
                f"cursor {target} < padded prefill length {padded_len}: the "
                "prompt's last token cannot land left of its own start"
            )
        start = target - p
        if start % ps != 0:
            raise ValueError(
                f"context start {start} not page-aligned (page_size {ps}) — "
                "use aligned_target for the cursor"
            )
        if m_shared % ps != 0 or m_shared > p:
            raise ValueError(
                f"m_shared ({m_shared}) must be a page multiple <= p ({p})"
            )
        n_sh = m_shared // ps
        if len(shared_ids) < n_sh:
            raise ValueError(
                f"{n_sh} shared pages needed, got {len(shared_ids)}"
            )
        if (self._tables[slot] != 0).any():
            raise ValueError(f"slot {slot} still maps pages (not freed?)")
        own_lo = (start + m_shared) // ps
        n_own = -(-(p - m_shared) // ps)
        own = self._alloc_pages(n_own)
        s0 = start // ps
        for j in range(n_sh):
            pid = int(shared_ids[j])
            self.alloc.ref(pid)
            self._tables[slot, s0 + j] = pid
        self.prefix_pages_shared_total += n_sh
        for j, pid in enumerate(own):
            self._tables[slot, own_lo + j] = pid
        self._slot_start[slot] = start
        # device roll-in: one compiled program per (row bucket, n_adm)
        n_adm = min(padded_len // ps + 1, n_log)
        lo_c = min(own_lo, n_log - n_adm)
        ids_arr = np.zeros((n_adm,), np.int32)
        for j, pid in enumerate(own):
            ids_arr[own_lo - lo_c + j] = pid
        self.cache = self._admit_fn(
            self.cache, row_cache,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(target - padded_len, jnp.int32),
            jnp.asarray(target, jnp.int32),
            jnp.asarray(ids_arr),
            jnp.asarray(lo_c, jnp.int32),
        )
        self.cursor = target
        self._upload_tables()

    def seed_row(self, page_ids: Sequence[int], m: int, start: int):
        """Batch-1 row whose columns [start, start+m) read the shared pages
        — the zero-copy prefix hit's suffix-prefill substrate. Pool pages
        are gathered for COMPUTE only (nothing allocated, nothing written;
        ``PageAllocator.copy_bytes`` untouched)."""
        if self.cache is None:
            raise RuntimeError("no cache allocated yet (nothing to seed from)")
        return self._seed_fn(
            self.cache["pool"],
            jnp.asarray(np.asarray(page_ids, np.int32)),
            jnp.asarray(m, jnp.int32),
            jnp.asarray(start, jnp.int32),
        )

    # --- disaggregated prefill/decode handoff (ISSUE 14) --------------------

    def stage_context(self, row_cache, p: int, padded: int) -> StagedContext:
        """Park a prefill row's context in the pool with no slot bound:
        allocate ``ceil(p / page_size)`` pages, scatter the row's context
        K/V into them page-aligned from column 0, and hold one reference
        per page until a handoff maps them (``map_staged``) or the caller
        releases them. This is the prefill worker's half of the
        disaggregated handoff — the decode side then binds the pages by
        block-table mapping alone."""
        if self.cache is None:
            if self.cursor > 0:
                raise RuntimeError(
                    "cache collection missing mid-flight (cursor "
                    f"{self.cursor}): a take() was never paired with "
                    "update_after_decode/restore"
                )
            self.allocate_from(row_cache)
        if p < 1 or p > padded:
            raise ValueError(f"bad staged context length p={p} (padded "
                             f"{padded})")
        n = -(-p // self.page_size)
        ids = self._alloc_pages(n)
        for pid in ids:
            self._staged[pid] = self._staged.get(pid, 0) + 1
        self.cache = self._stage_fn(
            self.cache, row_cache,
            jnp.asarray(p - padded, jnp.int32),  # context start -> column 0
            jnp.asarray(np.asarray(ids, np.int32)),
        )
        return StagedContext(tuple(int(i) for i in ids), p, padded)

    def staged_live(self, staged: StagedContext) -> bool:
        """Whether a staged context's pages are all still held and
        un-quarantined — a salvaged-recovery or page-poison event between
        stage and handoff voids it (the caller re-prefills)."""
        return bool(staged.page_ids) and all(
            self._staged.get(int(pid), 0) > 0
            and int(pid) not in self.alloc._quarantined
            for pid in staged.page_ids
        )

    def map_staged(self, slot: int, staged: StagedContext,
                   cursor: int) -> None:
        """Bind a staged context to ``slot`` at ``cursor`` as a PAGE-TABLE
        operation: the staging holds transfer to the slot's block-table
        mappings (no refcount motion, no K/V byte moves —
        ``PageAllocator.copy_bytes`` provably untouched) and one small
        jitted program sets the slot's kv_valid/cursor metadata. The
        context start ``cursor - p`` must be page-aligned."""
        if not self.staged_live(staged):
            raise ValueError(
                "staged context is no longer live (pool recovery or page "
                "quarantine voided it) — re-prefill"
            )
        p = staged.p
        start = cursor - p
        if start < 0 or start % self.page_size != 0:
            raise ValueError(
                f"handoff cursor {cursor} puts the context start at "
                f"{start} — not page-aligned (page_size {self.page_size})"
            )
        if (self._tables[slot] != 0).any():
            raise ValueError(f"slot {slot} still maps pages (not freed?)")
        s0 = start // self.page_size
        for j, pid in enumerate(staged.page_ids):
            pid = int(pid)
            # ref TRANSFER: the staging hold becomes the table mapping
            holds = self._staged.get(pid, 0)
            if holds <= 1:
                self._staged.pop(pid, None)
            else:
                self._staged[pid] = holds - 1
            self._tables[slot, s0 + j] = pid
        self._slot_start[slot] = start
        self.cache = self._map_fn(
            self.cache,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(start, jnp.int32),
            jnp.asarray(p, jnp.int32),
            jnp.asarray(cursor, jnp.int32),
        )
        self.cursor = cursor
        self._upload_tables()
        staged.page_ids = ()

    def release_staged(self, staged: StagedContext) -> None:
        """Drop an unconsumed staged context (handoff abandoned): the
        staging holds release and unshared pages flow back to the free
        list. VOID-safe: pages whose staged hold is already gone (pool
        recovery cleared ``_staged`` and dropped every hold) are skipped —
        a deref there would raise inside the caller's fallback path, or
        worse steal a reference from a page since re-allocated to another
        request."""
        for pid in staged.page_ids:
            pid = int(pid)
            holds = self._staged.get(pid, 0)
            if holds <= 0:
                continue  # voided by recovery: nothing left to release
            if holds == 1:
                self._staged.pop(pid, None)
            else:
                self._staged[pid] = holds - 1
            self.alloc.deref(pid)
        staged.page_ids = ()

    def export_pages(self, staged: StagedContext) -> ExportedContext:
        """Read a staged context's raw page blocks out of the pool (k/v
        and any quantized scale siblings, in pool storage form) for a
        DISTINCT decode pool to import — the device-transfer fallback when
        prefill and decode do not share a pool."""
        if not self.staged_live(staged):
            raise ValueError("staged context is no longer live")
        from neuronx_distributed_tpu.utils.tree import path_keys

        ids = jnp.asarray(np.asarray(staged.page_ids, np.int32))
        items = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            self.cache["pool"]
        )[0]:
            keys = tuple(path_keys(path))
            base = pool_scale_base(keys[-1]) or keys[-1]
            if base in ("k", "v"):
                pax = leaf.ndim - 4
                items.append((keys, jnp.take(leaf, ids, axis=pax)))
        return ExportedContext(
            items=items, n_pages=len(staged.page_ids),
            p=staged.p, padded=staged.padded,
        )

    def import_pages(self, exported: ExportedContext) -> StagedContext:
        """Write exported page blocks into THIS pool as a fresh staged
        context — a REAL device transfer, charged to
        ``PageAllocator.copy_bytes`` (the accounting that proves the
        shared-pool handoff moved nothing)."""
        if self.cache is None:
            raise RuntimeError(
                "import_pages needs an allocated pool — serve one "
                "admission first (or share the prefill worker's pool)"
            )
        from neuronx_distributed_tpu.modules.attention import _rebuild_tree

        ids = self._alloc_pages(exported.n_pages)
        for pid in ids:
            self._staged[pid] = self._staged.get(pid, 0) + 1
        blocks = _rebuild_tree(exported.items)
        self.cache = self._import_fn(
            self.cache, blocks, jnp.asarray(np.asarray(ids, np.int32))
        )
        self.alloc.copy_bytes += sum(
            int(block.nbytes) for _, block in exported.items
        )
        return StagedContext(
            tuple(int(i) for i in ids), exported.p, exported.padded
        )

    def spill_pages(self, ids: Sequence[int]):
        """Tiered KV, device->host half (ISSUE 19): pull the pinned prefix
        pages ``ids`` out of the pool as raw storage blocks — k/v pages
        plus any quantized scale siblings, exactly ``export_pages``'s
        layout — for the :class:`~neuronx_distributed_tpu.serving.tiering.
        HostPageStore`. One batched gather per pool leaf, then ONE explicit
        device->host pull of the whole batch. Returns ``(items, nbytes)``
        with host-numpy blocks. Runs only on the reclaim valve (a page-
        pressure event, never a steady chunk), so the pinned per-chunk
        budgets are untouched."""
        if self.cache is None:
            raise RuntimeError("spill needs an allocated pool")
        from neuronx_distributed_tpu.utils.tree import path_keys

        dev_ids = jnp.asarray(np.asarray(ids, np.int32))
        items = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            self.cache["pool"]
        )[0]:
            keys = tuple(path_keys(path))
            base = pool_scale_base(keys[-1]) or keys[-1]
            if base in ("k", "v"):
                pax = leaf.ndim - 4
                items.append((keys, jnp.take(leaf, dev_ids, axis=pax)))
        # the spill's ONE sync: every leaf's gathered block rides a single
        # batched pull (the gathers above dispatched async)
        # graftlint: ok[GL02] tiered spill (ISSUE 19): one batched
        # device->host pull per reclaim event — off the steady chunk path,
        # the documented spill transfer
        host_blocks = jax.device_get([block for _, block in items])
        out = [
            (keys, np.asarray(block))
            for (keys, _), block in zip(items, host_blocks)
        ]
        return out, sum(int(b.nbytes) for _, b in out)

    def prefetch_pages(self, items, n_pages: int) -> List[int]:
        """Tiered KV, host->device half (ISSUE 19): write host-tier page
        blocks back into the pool at freshly allocated ids and adopt each
        page's born reference AS a prefix pin — the re-homed entry is then
        indistinguishable from one whose pages never left the device
        (``pages_live``/``unpin_pages``/``check()`` all reconcile
        unchanged). The write rides the existing jitted import program:
        host->device dispatch only, NO sync — it overlaps whatever decode
        chunk is in flight, which is the whole point. NOT charged to
        ``copy_bytes``: that meter proves device-side CoW sharing moved
        nothing; tier traffic has its own accounting."""
        if self.cache is None:
            raise RuntimeError(
                "prefetch needs an allocated pool — serve one admission "
                "first (a host-tier hit before any pool exists would have "
                "nothing to write into)"
            )
        from neuronx_distributed_tpu.modules.attention import _rebuild_tree

        ids = self._alloc_pages(n_pages)
        blocks = _rebuild_tree(list(items))
        self.cache = self._import_fn(
            self.cache, blocks, jnp.asarray(np.asarray(ids, np.int32))
        )
        for pid in ids:
            # alloc born the page at refcount 1; that reference IS the pin
            self._pins[int(pid)] = self._pins.get(int(pid), 0) + 1
        return [int(pid) for pid in ids]

    def ensure_decode_window(self, active_slots, width: int) -> bool:
        """Map real pages under every active slot's next write window
        (columns ``[cursor, cursor + width)``) before a chunk dispatch.
        Returns False when the pool cannot cover it even after reclaiming
        prefix entries — the page-pressure wall (the engine preempts and
        rewinds, exactly like the cursor wall)."""
        if self.cache is None or len(active_slots) == 0:
            return True
        ps, n_log = self.page_size, self.pages_per_row
        lo = self.cursor // ps
        hi = min(n_log, -(-(self.cursor + width) // ps))
        need = [
            (int(s), j)
            for s in active_slots
            for j in range(lo, hi)
            if self._tables[int(s), j] == 0
        ]
        if not need:
            return True
        try:
            ids = self._alloc_pages(len(need))
        except PageExhausted:
            return False
        for (s, j), pid in zip(need, ids):
            self._tables[s, j] = pid
        self._upload_tables()
        return True

    def free(self, slot: int) -> None:
        """Clear the slot's validity row, deref every page it maps (shared
        pages survive through their other holders/pins; exclusive pages
        return to the free list immediately), and return the slot to the
        rotation."""
        if self.cache is not None:
            self.cache = self._free_fn(self.cache, jnp.asarray(slot, jnp.int32))
        row = self._tables[slot]
        for pid in row[row != 0]:
            self.alloc.deref(int(pid))
        self._tables[slot] = 0
        self._slot_start[slot] = None
        self._upload_tables()
        if slot not in self._quarantined and slot not in self._free:
            self._free.append(slot)
            self._free.sort()

    def take(self):
        cache, self.cache = self.cache, None
        return cache

    def restore(self, cache) -> None:
        self.cache = cache

    def _release_all_mappings(self) -> None:
        for slot in range(self.num_slots):
            row = self._tables[slot]
            for pid in row[row != 0]:
                self.alloc.deref(int(pid))
            self._tables[slot] = 0
            self._slot_start[slot] = None

    def recover(self, cache) -> bool:
        """Post-failed-dispatch salvage (the SlotCacheManager contract):
        every slot has been vacated, so all block-table mappings are
        dropped either way; surviving storage is invalidated in place,
        consumed storage falls to lazy reallocation. Prefix pins are the
        ENGINE's to resolve: on pool loss it clears the store, whose
        eviction hook releases every pin (the pinned content is gone)."""
        consumed = cache is None or any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree_util.tree_leaves(cache)
        )
        self.cursor = 0
        self._release_all_mappings()
        # staged handoff contexts are VOID either way: their holder (the
        # disaggregation server) observes staged_live() False and
        # re-prefills — recovery must not leave holds that block the pool
        for pid, holds in list(self._staged.items()):
            for _ in range(holds):
                self.alloc.deref(pid)
        self._staged.clear()
        # prefetch holds are VOID too: on pool loss the engine clears the
        # prefix store, whose eviction hook releases the pins themselves —
        # a surviving hold would permanently shrink the reclaimable sum
        self._prefetch_hold.clear()
        if consumed:
            self.cache = None
            return False
        self.cache = self._reset_fn(cache)
        self._upload_tables()
        return True

    def release_all_slots(self) -> None:
        self._free = [
            s for s in range(self.num_slots) if s not in self._quarantined
        ]

    def update_after_decode(self, new_cache, steps: int = 1) -> None:
        self.cache = new_cache
        self.cursor += steps

    def reset(self) -> None:
        """Rewind the cursor, invalidate every slot's context, and release
        every block-table mapping (drain / preemption — pages flow back to
        the free list unless a prefix pin or another holder keeps them;
        pinned page CONTENT is untouched, so entries stay servable)."""
        self.cursor = 0
        self._release_all_mappings()
        if self.cache is not None:
            self.cache = self._reset_fn(self.cache)
            self._upload_tables()

    # --- invariants ---------------------------------------------------------

    def check(self) -> None:
        """The page-leak/ref-count invariant: every page is exactly one of
        free / table-mapped / prefix-pinned / quarantined / reserved, ref
        counts reconcile with the mappers + pins, no slot double-maps a
        page, and the free list is duplicate-free. AssertionError with the
        offending page on any violation."""
        a = self.alloc
        free = set(a._free)
        assert len(free) == len(a._free), "free list has duplicates"
        assert 0 not in free and 0 not in a._refs and 0 not in self._pins, (
            "reserved null page 0 entered circulation"
        )
        mapped: Dict[int, int] = {}
        for s in range(self.num_slots):
            row = [int(p) for p in self._tables[s] if p != 0]
            assert len(row) == len(set(row)), (
                f"slot {s} double-maps a page: {row}"
            )
            for pid in row:
                mapped[pid] = mapped.get(pid, 0) + 1
        for pid in range(1, a.num_pages):
            expect = (
                mapped.get(pid, 0) + self._pins.get(pid, 0)
                + self._staged.get(pid, 0)
            )
            have = a.refcount(pid)
            assert have == expect, (
                f"page {pid}: refcount {have} != mapped({mapped.get(pid, 0)})"
                f" + pinned({self._pins.get(pid, 0)})"
                f" + staged({self._staged.get(pid, 0)})"
            )
            states = [
                pid in free,
                expect > 0,
                pid in a._quarantined and expect == 0,
            ]
            assert sum(states) == 1, (
                f"page {pid} is not exactly one of free/referenced/"
                f"quarantined: free={pid in free} refs={have} "
                f"quarantined={pid in a._quarantined}"
            )
        # tiered KV (ISSUE 19): a prefetch hold is an overlay on a PINNED
        # page, never a reference of its own — a hold on an unpinned page
        # means the release path lost track of a claimed prefetch
        for pid in self._prefetch_hold:
            assert self._pins.get(pid, 0) > 0, (
                f"page {pid} carries a prefetch hold but no prefix pin"
            )

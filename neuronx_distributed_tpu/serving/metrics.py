"""Serving metrics: per-request latency breakdown + engine counters.

Everything is plain host-side accounting (the engine clock is injectable for
deterministic tests) exported as a dict ``snapshot()``; when the engine holds
a ``utils.timeline.Timeline``, per-step occupancy and queue depth also land
on counter tracks next to the prefill/decode duration events, so one Perfetto
view shows the whole scheduling story.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Dict, Optional


def _mean(xs):
    return sum(xs) / len(xs) if xs else 0.0


def _p95(xs):
    if not xs:
        return 0.0
    ordered = sorted(xs)
    return ordered[max(0, math.ceil(0.95 * len(ordered)) - 1)]


class ServingMetrics:
    """Aggregates the engine's request lifecycle events."""

    def __init__(self, num_slots: int = 0):
        self.num_slots = num_slots
        # engine counters
        self.steps = 0  # decode steps executed (chunks × their used steps)
        self.chunks = 0  # decode dispatches (== host syncs on the hot path)
        self.prefills = 0
        self.decode_tokens = 0  # tokens produced by decode steps
        self.completed = 0
        self.cancelled = 0
        self.preemptions = 0
        # fault-tolerance counters (serving robustness layer)
        self.sheds = 0  # deadline/queue-timeout expiries (queued + in-flight)
        self.rejects = 0  # backpressure / drain / halt submit refusals
        self.quarantines = 0  # slots pulled from rotation for bad readbacks
        self.dispatch_retries = 0  # failed decode dispatches that recovered
        self.recoveries = 0  # completed requeue-and-resume recoveries
        self.prefill_failures = 0
        self.failed = 0  # requests terminated in FAILED (for cause)
        self.timed_out = 0  # requests terminated in TIMED_OUT
        self.health = "ok"  # engine-owned mirror of ServingEngine.health()
        # prefix-cache counters (shared-prompt KV reuse on the admission path)
        self.prefix_hits = 0  # admissions that reused a stored prefix
        self.prefix_misses = 0  # admissions that ran the full prefill
        self.prefix_tokens_reused = 0  # Σ matched prefix lengths over hits
        self.prefix_evictions = 0  # LRU + validation/poison evictions
        self.prefix_validation_failures = 0  # reuses rejected by checksum/shape
        # prefill latency (full AND suffix admissions): count/total ride
        # scalars; the p95 reads a bounded window of recent samples so a
        # long-lived engine neither grows without bound nor pays an O(n)
        # sort per snapshot. The per-kind wall split is the bench's
        # "prefill wall saved" source
        self.prefill_count = 0
        self.prefill_wall_s = 0.0
        self._prefill_recent = deque(maxlen=512)
        self.prefill_full_wall_s = 0.0
        self.prefill_suffix_wall_s = 0.0
        self.cursor_high_water = 0
        self.occupied_slot_steps = 0  # Σ active slots over decode steps
        # decode hot-path wall time, split at the host-sync boundary:
        # dispatch = enqueue the jitted chunk (donated, async), readback =
        # block on the chunk's token block (the ONE sync per chunk)
        self.decode_dispatch_s = 0.0
        self.decode_readback_s = 0.0
        # per-request
        self._requests: Dict[int, dict] = {}

    # --- request lifecycle --------------------------------------------------

    def record_submit(self, req, now: float) -> None:
        self._requests[req.rid] = {
            "rid": req.rid,
            "prompt_len": int(len(req.prompt)),
            "submit_time": now,
        }

    def record_admit(self, req, now: float) -> None:
        r = self._requests[req.rid]
        # first admission sets the queue wait; re-admissions after preemption
        # keep the original (the request never left the engine's care)
        r.setdefault("admit_time", now)
        r.setdefault("queue_wait", now - r["submit_time"])
        self.prefills += 1

    def record_first_token(self, req, now: float) -> None:
        r = self._requests[req.rid]
        r["first_token_time"] = now
        r["ttft"] = now - r["submit_time"]

    def record_finish(self, req, now: float) -> None:
        r = self._requests[req.rid]
        r["finish_time"] = now
        r["latency"] = now - r["submit_time"]
        r["tokens"] = len(req.tokens)
        decode_span = now - r.get("first_token_time", now)
        # tokens after the first are decode-step products
        r["decode_tokens_per_sec"] = (
            (len(req.tokens) - 1) / decode_span if decode_span > 0 else 0.0
        )
        r["preemptions"] = req.preemptions
        self.completed += 1

    def record_cancel(self, req, now: float) -> None:
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["cancelled"] = True
        self.cancelled += 1

    def record_preemption(self, req) -> None:
        self.preemptions += 1

    # --- fault tolerance ----------------------------------------------------

    def record_shed(self, req, now: float, where: str) -> None:
        """A request timed out — ``where`` is ``"queue"`` (shed before
        prefill) or ``"inflight"`` (deadline hit at a chunk boundary)."""
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["timed_out"] = True
            r["shed_where"] = where
            r["tokens"] = len(req.tokens)
        self.sheds += 1
        self.timed_out += 1

    def record_reject(self, queue_depth: int, reason: str) -> None:
        self.rejects += 1

    def record_quarantine(self, slot: int, rid) -> None:
        self.quarantines += 1

    def record_dispatch_retry(self) -> None:
        self.dispatch_retries += 1

    def record_recovery(self, requeued: int) -> None:
        self.recoveries += 1

    def record_failed(self, req, now: float, kind: str = "engine") -> None:
        """A request the engine failed for cause (``req.error`` has the
        reason): ``kind`` is ``"prefill"`` (OOM-like admission fault) or
        ``"quarantine"`` (poisoned slot under the fail policy)."""
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["failed"] = True
            r["failed_kind"] = kind
        self.failed += 1
        if kind == "prefill":
            self.prefill_failures += 1

    # --- prefix cache -------------------------------------------------------

    def record_prefix_hit(self, matched: int, prompt_len: int) -> None:
        """An admission reused ``matched`` stored prefix tokens of a
        ``prompt_len``-token context (only the tail was prefilled)."""
        self.prefix_hits += 1
        self.prefix_tokens_reused += matched

    def record_prefix_miss(self) -> None:
        self.prefix_misses += 1

    def record_prefix_eviction(self, n: int = 1) -> None:
        self.prefix_evictions += n

    def record_prefix_validation_failure(self) -> None:
        """A stored entry failed its reuse-time checksum/shape validation —
        it was evicted and the admission fell back to a full prefill."""
        self.prefix_validation_failures += 1

    def record_prefill_wall(self, seconds: float, kind: str = "full") -> None:
        """Wall time of one successful prefill dispatch (``kind`` is
        ``"full"`` or ``"suffix"``); feeds the count/mean/p95 latency stats
        and the per-kind split in :meth:`snapshot`."""
        self.prefill_count += 1
        self.prefill_wall_s += seconds
        self._prefill_recent.append(seconds)
        if kind == "suffix":
            self.prefill_suffix_wall_s += seconds
        else:
            self.prefill_full_wall_s += seconds

    # --- engine step --------------------------------------------------------

    def record_decode_step(self, active_slots: int, cursor: int) -> None:
        """Single-step accounting — the chunk-size-1 special case."""
        self.record_decode_chunk(active_slots, 1, cursor, active_slots)

    def record_decode_chunk(
        self,
        tokens: int,
        steps: int,
        cursor: int,
        active_slots: int,
        dispatch_s: float = 0.0,
        readback_s: float = 0.0,
    ) -> None:
        """One fused decode chunk: ``tokens`` DELIVERED to requests across
        ``steps`` executed scan steps by ``active_slots`` slots held at
        dispatch. Occupancy counts slots HELD, not tokens — a slot frozen
        mid-chunk (early EOS) still owns its cache row until the chunk
        boundary, so it occupies all ``steps``. ``dispatch_s``/
        ``readback_s`` split the wall time around the chunk's single host
        sync."""
        self.chunks += 1
        self.steps += steps
        self.decode_tokens += tokens
        self.occupied_slot_steps += active_slots * steps
        self.cursor_high_water = max(self.cursor_high_water, cursor)
        self.decode_dispatch_s += dispatch_s
        self.decode_readback_s += readback_s

    # --- export -------------------------------------------------------------

    @property
    def mean_occupancy(self) -> float:
        """Mean active slots per decode step (≤ num_slots)."""
        return self.occupied_slot_steps / self.steps if self.steps else 0.0

    def request_snapshot(self, rid: int) -> Optional[dict]:
        r = self._requests.get(rid)
        return dict(r) if r is not None else None

    def snapshot(self) -> dict:
        """Plain-dict export (log lines, tests, dashboards)."""
        done = [r for r in self._requests.values() if "latency" in r]
        ttfts = [r["ttft"] for r in self._requests.values() if "ttft" in r]
        waits = [
            r["queue_wait"] for r in self._requests.values()
            if "queue_wait" in r
        ]
        decode_wall = self.decode_dispatch_s + self.decode_readback_s
        return {
            "num_slots": self.num_slots,
            "steps": self.steps,
            "chunks": self.chunks,
            "decode_dispatch_s": self.decode_dispatch_s,
            "decode_readback_s": self.decode_readback_s,
            "chunk_tokens_per_sec": (
                self.decode_tokens / decode_wall if decode_wall > 0 else 0.0
            ),
            "prefills": self.prefills,
            "decode_tokens": self.decode_tokens,
            "completed": self.completed,
            "cancelled": self.cancelled,
            "preemptions": self.preemptions,
            "sheds": self.sheds,
            "rejects": self.rejects,
            "quarantines": self.quarantines,
            "dispatch_retries": self.dispatch_retries,
            "recoveries": self.recoveries,
            "prefill_failures": self.prefill_failures,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (
                self.prefix_hits / (self.prefix_hits + self.prefix_misses)
                if self.prefix_hits + self.prefix_misses else 0.0
            ),
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "prefix_evictions": self.prefix_evictions,
            "prefix_validation_failures": self.prefix_validation_failures,
            "prefill_count": self.prefill_count,
            "prefill_wall_s": self.prefill_wall_s,
            "prefill_mean_s": (
                self.prefill_wall_s / self.prefill_count
                if self.prefill_count else 0.0
            ),
            "prefill_p95_s": _p95(self._prefill_recent),
            "prefill_full_wall_s": self.prefill_full_wall_s,
            "prefill_suffix_wall_s": self.prefill_suffix_wall_s,
            "failed": self.failed,
            "timed_out": self.timed_out,
            "health": self.health,
            "cursor_high_water": self.cursor_high_water,
            "mean_occupancy": self.mean_occupancy,
            "mean_ttft": _mean(ttfts),
            "max_ttft": max(ttfts) if ttfts else 0.0,
            "mean_queue_wait": _mean(waits),
            "mean_latency": _mean([r["latency"] for r in done]),
            "mean_decode_tokens_per_sec": _mean(
                [r["decode_tokens_per_sec"] for r in done]
            ),
        }

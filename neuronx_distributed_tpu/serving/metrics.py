"""Serving metrics: per-request latency breakdown + engine counters.

Everything is plain host-side accounting (the engine clock is injectable for
deterministic tests) exported as a dict ``snapshot()``; when the engine holds
a ``utils.timeline.Timeline``, per-step occupancy and queue depth also land
on counter tracks next to the prefill/decode duration events, so one Perfetto
view shows the whole scheduling story.

Since ISSUE 8 the counters live in a shared
:class:`~neuronx_distributed_tpu.observability.registry.MetricsRegistry`
(pass one in to co-export serving and trainer metrics from a single
Prometheus surface; ``metrics.registry.prometheus_text()`` is the scrape
payload). The attribute surface is unchanged — ``metrics.steps`` etc. read
through to the registry — and the ``snapshot()`` keys are preserved
bit-for-bit in name and type.

Latency percentiles come from log-bucketed histograms (exact to the bucket,
fixed memory): the prefill p95 no longer reads a 512-sample recent window —
whose value drifted with stream phase on long runs — and new TTFT/TPOT
histograms (``ttft_p50_s``..``tpot_p99_s`` in the snapshot) feed the SLO
scheduling work the ROADMAP names. Recording stays sync-free: every sample
is a host scalar the engine already owned.

Tenant attribution + SLO accounting (ISSUE 11):

* Every request carries ``tenant``/``priority`` (threaded through
  ``ServingEngine.submit``); TTFT, TPOT, and queue-wait additionally land
  in per-tenant labeled histogram families
  (``serving_tenant_ttft_s{tenant="..."}``), and sheds / timeouts /
  rejects / failures / completions / delivered tokens get per-tenant
  attribution counters — the ``snapshot()``'s ``tenants`` breakdown.
* ``slo=`` (an :class:`~neuronx_distributed_tpu.observability.slo.SLOSpec`
  or a ``{tenant: SLOSpec}`` dict) attaches an
  :class:`~neuronx_distributed_tpu.observability.slo.SLOTracker`: each
  request is classified once at its terminal state (attained / violated),
  goodput = tokens from attaining requests per second, all per tenant —
  the ``snapshot()``'s ``slo`` block and the labeled ``serving_slo_*``
  Prometheus families.
* ``engine_label=`` retires the PR 7 one-engine-per-registry restriction:
  with a label, EVERY serving metric registers as a child of an
  ``engine``-labeled family, so two labeled engines share one registry
  (one scrape endpoint for a multi-engine host) without merging a single
  counter. Unlabeled engines keep the loud rejection.

All of it rides the same host scalars — zero added device→host syncs
(re-pinned in tests/serving/test_host_sync.py with tenants + SLO on).
"""

from __future__ import annotations

import weakref
from typing import Dict, Optional

from neuronx_distributed_tpu.observability.registry import (
    MetricFamily,
    MetricsRegistry,
    MetricsView,
)
from neuronx_distributed_tpu.observability.slo import SLOSpec, SLOTracker
from neuronx_distributed_tpu.observability.spec_stats import SpecStats


def _mean(xs):
    return sum(xs) / len(xs) if xs else 0.0


# (attribute, registry metric name, int-valued) — the engine/test-visible
# counter surface, all backed by registry counters
_COUNTERS = (
    ("steps", "serving_steps", True),
    ("chunks", "serving_chunks", True),
    ("prefills", "serving_prefills", True),
    ("decode_tokens", "serving_decode_tokens", True),
    ("completed", "serving_completed", True),
    ("cancelled", "serving_cancelled", True),
    ("preemptions", "serving_preemptions", True),
    ("sheds", "serving_sheds", True),
    ("rejects", "serving_rejects", True),
    ("quarantines", "serving_quarantines", True),
    ("dispatch_retries", "serving_dispatch_retries", True),
    ("recoveries", "serving_recoveries", True),
    ("prefill_failures", "serving_prefill_failures", True),
    ("failed", "serving_failed", True),
    ("timed_out", "serving_timed_out", True),
    ("prefix_hits", "serving_prefix_hits", True),
    ("prefix_misses", "serving_prefix_misses", True),
    ("prefix_tokens_reused", "serving_prefix_tokens_reused", True),
    ("prefix_evictions", "serving_prefix_evictions", True),
    ("prefix_validation_failures", "serving_prefix_validation_failures", True),
    # paged KV (kv_page_size=): pages retired for poison, and pool pages
    # mapped copy-on-write into a hitting slot (zero bytes moved)
    ("page_quarantines", "serving_page_quarantines", True),
    ("prefix_pages_shared", "serving_prefix_pages_shared", True),
    # tiered KV (ISSUE 19): host spill-tier traffic and its failure modes.
    # late = prefetch issued at rebind time instead of the queue pre-pass
    # (the overlap window was missed); wasted = prefetched pages whose
    # entry was evicted before any request consumed them
    ("kv_pages_spilled", "serving_kv_spill_pages", True),
    ("kv_spill_bytes", "serving_kv_spill_bytes", True),
    ("kv_spill_failures", "serving_kv_spill_failures", True),
    ("kv_pages_prefetched", "serving_kv_spill_prefetch_pages", True),
    ("kv_prefetch_bytes", "serving_kv_spill_prefetch_bytes", True),
    ("kv_prefetch_late", "serving_kv_spill_prefetch_late", True),
    ("kv_prefetch_wasted", "serving_kv_spill_prefetch_wasted", True),
    ("kv_prefetch_failures", "serving_kv_spill_prefetch_failures", True),
    ("kv_host_poisoned", "serving_kv_spill_host_poisoned", True),
    # elastic fabric (ISSUE 18): requests brought back by a warm restart
    ("restored", "serving_restored_requests", True),
    ("occupied_slot_steps", "serving_occupied_slot_steps", True),
    ("prefill_full_wall_s", "serving_prefill_full_wall_s", False),
    ("prefill_suffix_wall_s", "serving_prefill_suffix_wall_s", False),
    ("decode_dispatch_s", "serving_decode_dispatch_s", False),
    ("decode_readback_s", "serving_decode_readback_s", False),
)

_HEALTH_CODES = {"ok": 0, "degraded": 1, "draining": 2, "halted": 3}

# per-tenant attribution counters (ISSUE 11): attr suffix -> registry
# family name. Rejects/sheds/timeouts answer "WHO is being turned away",
# completed/decode_tokens feed the per-tenant goodput/throughput story
_TENANT_COUNTERS = (
    ("submitted", "serving_tenant_submitted"),
    ("completed", "serving_tenant_completed"),
    ("decode_tokens", "serving_tenant_decode_tokens"),
    ("sheds", "serving_tenant_sheds"),
    ("timed_out", "serving_tenant_timed_out"),
    ("rejects", "serving_tenant_rejects"),
    ("failed", "serving_tenant_failed"),
)


def _tenant_of(req) -> str:
    return getattr(req, "tenant", "default")


class ServingMetrics:
    """Aggregates the engine's request lifecycle events into a registry."""

    def __init__(self, num_slots: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 engine_label: Optional[str] = None,
                 slo=None):
        self.num_slots = num_slots
        self.engine_label = engine_label
        if registry is not None:
            existing = registry.get(_COUNTERS[0][1])
            if existing is not None:
                # an unlabeled second engine on the same registry would
                # SILENTLY merge its counters into the first's (and
                # last-writer-wins the export gauges). Labeled metric
                # families retire that restriction: when BOTH engines pass
                # a distinct engine_label, every serving metric is a child
                # of an `engine`-labeled family and the series stay
                # separate. Anything else still fails loudly. Sharing
                # across SUBSYSTEMS (serving_ + train_ prefixes) needs no
                # labels either way
                shareable = (
                    engine_label is not None
                    and isinstance(existing, MetricFamily)
                    and existing.label_names == ("engine",)
                    and not existing.has_child(engine_label)
                )
                if not shareable:
                    raise ValueError(
                        "registry already holds serving metrics (another "
                        "ServingEngine registered into it) — pass a "
                        "distinct engine_label= on every engine to share "
                        "one registry via labeled families, or a distinct "
                        "MetricsRegistry per engine"
                    )
        self.registry = registry if registry is not None else MetricsRegistry()

        # with an engine label, every serving metric resolves through an
        # engine-scoped MetricsView (a family child keyed by the label);
        # without one, the original unlabeled series. The record paths
        # below are identical either way — children ARE plain
        # Counter/Gauge/Histogram instances. The view is the ONE owner of
        # the labeling scheme (SpecStats and SLOTracker ride it too)
        self.view = (
            MetricsView(self.registry, ("engine",), (engine_label,))
            if engine_label is not None else MetricsView(self.registry)
        )
        own_counter = self.view.counter
        own_histogram = self.view.histogram
        self.own_gauge = self.view.gauge  # the engine's export gauges
        self._c = {}
        for attr, name, is_int in _COUNTERS:
            self._c[attr] = (own_counter(name), is_int)
        # latency histograms: log-bucketed, fixed memory, quantiles exact
        # to the bucket (observability/registry.py) — prefill feeds the
        # legacy prefill_p95_s key; TTFT/TPOT feed the SLO roadmap item
        self._h_prefill = own_histogram(
            "serving_prefill_latency_s",
            help="wall time of one successful prefill dispatch (s)",
        )
        self._h_ttft = own_histogram(
            "serving_ttft_s", help="submit -> first token (s)"
        )
        self._h_tpot = own_histogram(
            "serving_tpot_s",
            help="per-request mean time per output token after the first (s)",
        )
        self._h_queue_wait = own_histogram(
            "serving_queue_wait_s", help="submit -> first admission (s)"
        )
        self._h_restore_downtime = own_histogram(
            "serving_restore_downtime_s",
            help="snapshot -> restore_serving_state clock gap (s): how "
                 "long a warm-restarted replica's work was dark",
        )
        # tiered KV (ISSUE 19): transfer batch sizes — spill efficiency
        # lives in pages-per-event, not event counts
        self._h_spill_batch = own_histogram(
            "serving_kv_spill_batch_pages",
            help="pages moved device->host per spill event",
        )
        self._h_prefetch_batch = own_histogram(
            "serving_kv_spill_prefetch_batch_pages",
            help="pages moved host->device per prefetch event",
        )
        self._g_cursor = self.view.gauge(
            "serving_cursor_high_water", help="highest shared cache cursor seen"
        )
        self._g_health = self.view.gauge(
            "serving_health", help="0=ok 1=degraded 2=draining 3=halted"
        )
        self._g_health.set_fn(lambda: _HEALTH_CODES.get(self.health, -1))
        # per-tenant labeled families (tenant label; engine+tenant when
        # this engine itself is labeled — the view prepends its scope).
        # Registered up front so the exposition surface exists before
        # traffic arrives; children materialize per tenant on first use
        self._tc: Dict[str, MetricFamily] = {
            attr: self.view.family("counter", name)
            for attr, name in _TENANT_COUNTERS
        }
        self._th_ttft = self.view.family(
            "histogram", "serving_tenant_ttft_s",
            help="submit -> first token per tenant (s)",
        )
        self._th_tpot = self.view.family(
            "histogram", "serving_tenant_tpot_s",
            help="per-request mean time per output token per tenant (s)",
        )
        self._th_queue_wait = self.view.family(
            "histogram", "serving_tenant_queue_wait_s",
            help="submit -> first admission per tenant (s)",
        )
        # tiered KV (ISSUE 19): which tier the matched prefix entry's
        # pages lived in when the hit was consumed — device (CoW share)
        # or host (spilled, prefetched back)
        self._f_hit_tier = self.view.family(
            "counter", "serving_prefix_hit_tier", labels=("tier",),
            help="prefix hits by residency tier of the matched entry",
        )
        self._tenants_seen = set()
        # SLO accounting (observability/slo.py): classify every request
        # once at its terminal state against its tenant's SLOSpec; export
        # attainment + goodput per tenant through the same registry
        if slo is not None and not isinstance(slo, SLOTracker):
            slo = SLOTracker(
                slo, registry=self.registry, prefix="serving_slo",
                view=self.view,
            )
        self.slo: Optional[SLOTracker] = slo
        # speculative-decoding acceptance stats: the SHARED recorder (solo
        # speculative_generate reports through the same class, so both
        # paths expose identical names/keys); always registered so the
        # snapshot surface is stable whether or not a draft model is bound
        self.spec = SpecStats(self.registry, prefix="spec", view=self.view)
        self.view.gauge("serving_num_slots").set(num_slots)
        self.health = "ok"  # engine-owned mirror of ServingEngine.health()
        self.cursor_high_water = 0
        # device-efficiency ledgers (ISSUE 12): attached weakly by the
        # engine so snapshot() can carry "programs"/"hbm" without a kept
        # metrics object pinning a retired engine's ledgers
        self._programs_ref = None
        self._hbm_ref = None
        # per-request
        self._requests: Dict[int, dict] = {}

    def attach_device_efficiency(self, programs, hbm) -> None:
        """Wire the engine's :class:`ProgramLedger`/:class:`HBMLedger`
        into ``snapshot()["programs"]``/``["hbm"]`` (weak references)."""
        self._programs_ref = weakref.ref(programs) if programs is not None else None
        self._hbm_ref = weakref.ref(hbm) if hbm is not None else None

    def _tenant_inc(self, attr: str, tenant: str, n=1) -> None:
        self._tenants_seen.add(tenant)
        self.view.child(self._tc[attr], tenant).inc(n)

    def _tenant_observe(self, family: MetricFamily, tenant: str,
                        value: float) -> None:
        self._tenants_seen.add(tenant)
        self.view.child(family, tenant).observe(value)

    def __getattr__(self, name):
        # counter attributes (``metrics.steps`` etc.) read through to the
        # registry; only consulted when no instance attribute exists
        c = self.__dict__.get("_c")
        if c is not None and name in c:
            counter, is_int = c[name]
            v = counter.value
            return int(v) if is_int else float(v)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def _inc(self, attr: str, n=1) -> None:
        self._c[attr][0].inc(n)

    # --- request lifecycle --------------------------------------------------

    def record_submit(self, req, now: float) -> None:
        tenant = _tenant_of(req)
        self._requests[req.rid] = {
            "rid": req.rid,
            "prompt_len": int(len(req.prompt)),
            "submit_time": now,
            "tenant": tenant,
            "priority": getattr(req, "priority", "standard"),
        }
        self._tenant_inc("submitted", tenant)
        if self.slo is not None:
            # goodput's denominator starts at the FIRST submit, not the
            # first finish — idle-tail truncation would inflate it
            self.slo.touch(now)

    def record_adopt(self, req, now: float) -> None:
        """Register a RE-HOMED request (the replica router moved it here
        from a halted replica): per-request bookkeeping only, keyed to the
        ORIGINAL submit time so TTFT/latency spans the request's whole
        life — the submit itself was already counted where it happened."""
        tenant = _tenant_of(req)
        self._requests[req.rid] = {
            "rid": req.rid,
            "prompt_len": int(len(req.prompt)),
            "submit_time": (
                req.submit_time if req.submit_time is not None else now
            ),
            "tenant": tenant,
            "priority": getattr(req, "priority", "standard"),
        }
        if req.first_token_time is not None:
            # the dead replica already streamed its first token — keep the
            # TTFT it measured out of this engine's histograms (it was
            # observed there) but make decode-span math exact here
            self._requests[req.rid]["first_token_time"] = (
                req.first_token_time
            )
        if self.slo is not None:
            self.slo.touch(now)

    def record_restore(self, n_requests: int, downtime_s: float) -> None:
        """Warm-restart accounting (ISSUE 18): how many requests
        ``restore_serving_state`` brought back and how long they were
        dark. The per-request bookkeeping itself rides
        :meth:`record_adopt` (restore calls it per request)."""
        if n_requests:
            self._inc("restored", n_requests)
        self._h_restore_downtime.observe(float(downtime_s))

    def record_admit(self, req, now: float) -> None:
        r = self._requests[req.rid]
        # first admission sets the queue wait; re-admissions after preemption
        # keep the original (the request never left the engine's care)
        r.setdefault("admit_time", now)
        if "queue_wait" not in r:
            r["queue_wait"] = now - r["submit_time"]
            self._h_queue_wait.observe(r["queue_wait"])
            self._tenant_observe(
                self._th_queue_wait, _tenant_of(req), r["queue_wait"]
            )
        self._inc("prefills")

    def record_first_token(self, req, now: float) -> None:
        r = self._requests[req.rid]
        r["first_token_time"] = now
        r["ttft"] = now - r["submit_time"]
        self._h_ttft.observe(r["ttft"])
        self._tenant_observe(self._th_ttft, _tenant_of(req), r["ttft"])

    def record_finish(self, req, now: float) -> None:
        r = self._requests[req.rid]
        tenant = _tenant_of(req)
        r["finish_time"] = now
        r["latency"] = now - r["submit_time"]
        r["tokens"] = len(req.tokens)
        decode_span = now - r.get("first_token_time", now)
        # tokens after the first are decode-step products
        r["decode_tokens_per_sec"] = (
            (len(req.tokens) - 1) / decode_span if decode_span > 0 else 0.0
        )
        # TPOT is undefined for single-token requests (None: an SLO TPOT
        # bound passes vacuously); a 0-span multi-chunk finish under a
        # virtual clock observes 0 (the histogram's zero bucket)
        tpot = (
            decode_span / (len(req.tokens) - 1)
            if len(req.tokens) > 1 else None
        )
        if tpot is not None:
            self._h_tpot.observe(tpot)
            self._tenant_observe(self._th_tpot, tenant, tpot)
        r["preemptions"] = req.preemptions
        self._inc("completed")
        self._tenant_inc("completed", tenant)
        self._tenant_inc("decode_tokens", tenant, len(req.tokens))
        if self.slo is not None:
            # the ONE terminal classification of a finished request —
            # preemption/recovery requeues re-admit but never re-finish,
            # so a requeued-then-finished request counts exactly once
            r["slo_attained"] = self.slo.record_finish(
                tenant, r.get("ttft"), tpot, len(req.tokens), now
            )

    def record_cancel(self, req, now: float) -> None:
        # a user cancellation is neither attained nor violated — the
        # engine met whatever contract the caller abandoned
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["cancelled"] = True
        self._inc("cancelled")

    def record_preemption(self, req) -> None:
        self._inc("preemptions")

    # --- fault tolerance ----------------------------------------------------

    def record_shed(self, req, now: float, where: str) -> None:
        """A request timed out — ``where`` is ``"queue"`` (shed before
        prefill) or ``"inflight"`` (deadline hit at a chunk boundary)."""
        tenant = _tenant_of(req)
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["timed_out"] = True
            r["shed_where"] = where
            r["tokens"] = len(req.tokens)
        self._inc("sheds")
        self._inc("timed_out")
        self._tenant_inc("sheds", tenant)
        self._tenant_inc("timed_out", tenant)
        if self.slo is not None:
            # tokens a shed request already streamed are wasted work:
            # total, never goodput
            self.slo.record_violation(
                tenant, now,
                reason=f"shed_{where}", tokens=len(req.tokens),
            )

    def record_reject(self, queue_depth: int, reason: str,
                      tenant: str = "default",
                      now: Optional[float] = None) -> None:
        """A submission refused at the door (queue full, draining,
        halted) — no Request exists, so the tenant (and the engine-clock
        timestamp) ride in directly. Rejected traffic is an SLO
        violation: shedding a tenant's load must never read as improving
        its attainment."""
        self._inc("rejects")
        self._tenant_inc("rejects", tenant)
        if self.slo is not None:
            self.slo.record_violation(tenant, now, reason="reject")

    def record_quarantine(self, slot: int, rid) -> None:
        self._inc("quarantines")

    def record_dispatch_retry(self) -> None:
        self._inc("dispatch_retries")

    def record_recovery(self, requeued: int) -> None:
        self._inc("recoveries")

    def record_failed(self, req, now: float, kind: str = "engine") -> None:
        """A request the engine failed for cause (``req.error`` has the
        reason): ``kind`` is ``"prefill"`` (OOM-like admission fault) or
        ``"quarantine"`` (poisoned slot under the fail policy)."""
        tenant = _tenant_of(req)
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["failed"] = True
            r["failed_kind"] = kind
        self._inc("failed")
        self._tenant_inc("failed", tenant)
        if kind == "prefill":
            self._inc("prefill_failures")
        if self.slo is not None:
            self.slo.record_violation(
                tenant, now, reason=f"failed_{kind}", tokens=len(req.tokens)
            )

    # --- prefix cache -------------------------------------------------------

    def record_prefix_hit(self, matched: int, prompt_len: int,
                          tier: str = "device") -> None:
        """An admission reused ``matched`` stored prefix tokens of a
        ``prompt_len``-token context (only the tail was prefilled).
        ``tier`` is where the entry's pages lived when the hit was
        consumed: ``"device"`` (resident, CoW share) or ``"host"``
        (spilled to the host tier and prefetched back)."""
        self._inc("prefix_hits")
        self._inc("prefix_tokens_reused", matched)
        self.view.child(self._f_hit_tier, tier).inc()

    def record_prefix_pages_shared(self, n: int) -> None:
        """A paged prefix hit mapped ``n`` pool pages copy-on-write into
        the admitted slot's block table (zero KV bytes copied)."""
        self._inc("prefix_pages_shared", n)

    def record_page_quarantine(self, page: int, victims: int) -> None:
        """A poisoned pool page was retired; ``victims`` requests mapping
        it were requeued (page-granular fault domain)."""
        self._inc("page_quarantines")

    def record_prefix_miss(self) -> None:
        self._inc("prefix_misses")

    def record_prefix_eviction(self, n: int = 1) -> None:
        self._inc("prefix_evictions", n)

    def record_prefix_validation_failure(self) -> None:
        """A stored entry failed its reuse-time checksum/shape validation —
        it was evicted and the admission fell back to a full prefill."""
        self._inc("prefix_validation_failures")

    # --- tiered KV (ISSUE 19) -----------------------------------------------

    def record_spill(self, pages: int, nbytes: int) -> None:
        """The reclaim valve moved a cold prefix entry's ``pages`` pool
        pages (``nbytes`` total) device->host in one batched pull."""
        self._inc("kv_pages_spilled", pages)
        self._inc("kv_spill_bytes", nbytes)
        self._h_spill_batch.observe(float(pages))

    def record_spill_failure(self) -> None:
        """A spill attempt failed (injected or real) — the entry degraded
        to plain eviction, the pre-tiering behavior."""
        self._inc("kv_spill_failures")

    def record_prefetch(self, pages: int, nbytes: int,
                        late: bool = False) -> None:
        """``pages`` spilled pages were written back device-side.
        ``late=True`` means the write happened at rebind time (the queue
        pre-pass missed it) — correct, but the overlap window was lost."""
        self._inc("kv_pages_prefetched", pages)
        self._inc("kv_prefetch_bytes", nbytes)
        if late:
            self._inc("kv_prefetch_late")
        self._h_prefetch_batch.observe(float(pages))

    def record_prefetch_failure(self) -> None:
        """A prefetch attempt failed — the entry was dropped and the
        admission fell back to a full prefill."""
        self._inc("kv_prefetch_failures")

    def record_prefetch_wasted(self, pages: int) -> None:
        """``pages`` prefetched pages were evicted before any request
        consumed them — the prefetch's work was thrown away."""
        self._inc("kv_prefetch_wasted", pages)

    def record_host_page_poisoned(self, n: int = 1) -> None:
        """A host-tier fetch was rejected because at least one of its
        pages failed the fingerprint check (bit rot / chaos poison)."""
        self._inc("kv_host_poisoned", n)

    def record_prefill_wall(self, seconds: float, kind: str = "full") -> None:
        """Wall time of one successful prefill dispatch (``kind`` is
        ``"full"`` or ``"suffix"``); feeds the latency histogram (count/
        mean/p95 in :meth:`snapshot`) and the per-kind wall split."""
        self._h_prefill.observe(seconds)
        if kind == "suffix":
            self._inc("prefill_suffix_wall_s", seconds)
        else:
            self._inc("prefill_full_wall_s", seconds)

    # --- engine step --------------------------------------------------------

    def record_decode_step(self, active_slots: int, cursor: int) -> None:
        """Single-step accounting — the chunk-size-1 special case."""
        self.record_decode_chunk(active_slots, 1, cursor, active_slots)

    def record_decode_chunk(
        self,
        tokens: int,
        steps: int,
        cursor: int,
        active_slots: int,
        dispatch_s: float = 0.0,
        readback_s: float = 0.0,
        spec_accepts=None,
        gamma: int = 0,
    ) -> None:
        """One fused decode chunk: ``tokens`` DELIVERED to requests across
        ``steps`` executed scan steps by ``active_slots`` slots held at
        dispatch. Occupancy counts slots HELD, not tokens — a slot frozen
        mid-chunk (early EOS) still owns its cache row until the chunk
        boundary, so it occupies all ``steps``. ``dispatch_s``/
        ``readback_s`` split the wall time around the chunk's single host
        sync.

        Speculative chunks (``steps`` = executed ROUNDS) additionally pass
        ``spec_accepts`` — one accepted-draft length per (live round, slot)
        pair, already host scalars from the chunk's single readback — and
        ``gamma``; the draft/verify split (drafted vs accepted vs wasted
        draft tokens) lands in the shared ``SpecStats`` recorder."""
        self._inc("chunks")
        self._inc("steps", steps)
        self._inc("decode_tokens", tokens)
        self._inc("occupied_slot_steps", active_slots * steps)
        if cursor > self.cursor_high_water:
            self.cursor_high_water = cursor
            self._g_cursor.set(cursor)
        self._inc("decode_dispatch_s", dispatch_s)
        self._inc("decode_readback_s", readback_s)
        if spec_accepts is not None:
            for a in spec_accepts:
                self.spec.record_round(int(a), gamma)

    def record_spec_fallback(self) -> None:
        """A speculative dispatch failed and the chunk was decoded
        non-speculatively instead (streams unaffected)."""
        self.spec.record_fallback()

    # --- export -------------------------------------------------------------

    @property
    def prefill_count(self) -> int:
        """Successful prefill dispatches (full + suffix)."""
        return self._h_prefill.count

    @property
    def prefill_wall_s(self) -> float:
        return float(self._h_prefill.sum)

    @property
    def mean_occupancy(self) -> float:
        """Mean active slots per decode step (≤ num_slots)."""
        return self.occupied_slot_steps / self.steps if self.steps else 0.0

    def request_snapshot(self, rid: int) -> Optional[dict]:
        r = self._requests.get(rid)
        return dict(r) if r is not None else None

    def _tenant_child_value(self, attr: str, tenant: str) -> int:
        fam = self._tc[attr]
        if not self.view.has_child(fam, tenant):
            return 0
        return int(self.view.child(fam, tenant).value)

    def tenant_latency(self, kind: str, tenant: str, q: float) -> float:
        """Live read of one tenant's latency quantile off its labeled
        histogram child (``kind`` = ``"ttft"`` | ``"tpot"`` |
        ``"queue_wait"``): the SLO-aware scheduler's early-warning signal
        (ISSUE 16) — the attainment tracker only classifies at finish
        time, but a burst's damage shows here first. READ-only (0.0 for a
        tenant that never recorded — never materializes an empty child)
        and pure host arithmetic over bucket counts: safe on the
        admission path, zero syncs."""
        fam = {
            "ttft": self._th_ttft,
            "tpot": self._th_tpot,
            "queue_wait": self._th_queue_wait,
        }[kind]
        if not self.view.has_child(fam, tenant):
            return 0.0
        return float(self.view.child(fam, tenant).percentile(q))

    def tenant_snapshot(self) -> Dict[str, dict]:
        """Per-tenant breakdown (tenant-sorted, deterministic keys):
        attribution counters + the tenant's latency percentiles off its
        labeled histogram children. READ-only: a tenant that never
        recorded a latency (e.g. only ever rejected at the door) reports
        0.0 percentiles without materializing empty histogram children —
        a snapshot must not change what the next scrape exports."""
        out: Dict[str, dict] = {}
        for tenant in sorted(self._tenants_seen):
            row = {
                attr: self._tenant_child_value(attr, tenant)
                for attr, _ in _TENANT_COUNTERS
            }
            for key, fam in (
                ("ttft", self._th_ttft),
                ("tpot", self._th_tpot),
            ):
                h = (
                    self.view.child(fam, tenant)
                    if self.view.has_child(fam, tenant) else None
                )
                for q in (50, 95, 99):
                    row[f"{key}_p{q}_s"] = (
                        h.percentile(q / 100.0) if h is not None else 0.0
                    )
            row["queue_wait_p95_s"] = (
                self.view.child(self._th_queue_wait, tenant).percentile(0.95)
                if self.view.has_child(self._th_queue_wait, tenant) else 0.0
            )
            out[tenant] = row
        return out

    def snapshot(self, analyze_programs: bool = True) -> dict:
        """Plain-dict export (log lines, tests, dashboards). Every key of
        the pre-registry snapshot is preserved in name and type; the
        percentile keys now read bucket-exact histogram quantiles, and the
        ``ttft_*``/``tpot_*`` families are new. ``analyze_programs=False``
        skips any not-yet-run program cost analysis (halt paths)."""
        programs = self._programs_ref() if self._programs_ref else None
        hbm = self._hbm_ref() if self._hbm_ref else None
        done = [r for r in self._requests.values() if "latency" in r]
        ttfts = [r["ttft"] for r in self._requests.values() if "ttft" in r]
        waits = [
            r["queue_wait"] for r in self._requests.values()
            if "queue_wait" in r
        ]
        decode_wall = self.decode_dispatch_s + self.decode_readback_s
        return {
            "num_slots": self.num_slots,
            "steps": self.steps,
            "chunks": self.chunks,
            "decode_dispatch_s": self.decode_dispatch_s,
            "decode_readback_s": self.decode_readback_s,
            "chunk_tokens_per_sec": (
                self.decode_tokens / decode_wall if decode_wall > 0 else 0.0
            ),
            "prefills": self.prefills,
            "decode_tokens": self.decode_tokens,
            "completed": self.completed,
            "cancelled": self.cancelled,
            "preemptions": self.preemptions,
            "sheds": self.sheds,
            "rejects": self.rejects,
            "quarantines": self.quarantines,
            "dispatch_retries": self.dispatch_retries,
            "recoveries": self.recoveries,
            "prefill_failures": self.prefill_failures,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (
                self.prefix_hits / (self.prefix_hits + self.prefix_misses)
                if self.prefix_hits + self.prefix_misses else 0.0
            ),
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "prefix_evictions": self.prefix_evictions,
            "prefix_validation_failures": self.prefix_validation_failures,
            "prefix_pages_shared": self.prefix_pages_shared,
            "page_quarantines": self.page_quarantines,
            # tiered KV (ISSUE 19): spill/prefetch traffic, failure modes,
            # and the per-tier split of consumed prefix hits
            "kv_pages_spilled": self.kv_pages_spilled,
            "kv_spill_bytes": self.kv_spill_bytes,
            "kv_spill_failures": self.kv_spill_failures,
            "kv_pages_prefetched": self.kv_pages_prefetched,
            "kv_prefetch_bytes": self.kv_prefetch_bytes,
            "kv_prefetch_late": self.kv_prefetch_late,
            "kv_prefetch_wasted": self.kv_prefetch_wasted,
            "kv_prefetch_failures": self.kv_prefetch_failures,
            "kv_host_poisoned": self.kv_host_poisoned,
            "prefix_hit_tier": {
                tier: int(self.view.child(self._f_hit_tier, tier).value)
                for tier in ("device", "host")
                if self.view.has_child(self._f_hit_tier, tier)
            },
            "prefill_count": self.prefill_count,
            "prefill_wall_s": self.prefill_wall_s,
            "prefill_mean_s": self._h_prefill.mean,
            "prefill_p95_s": self._h_prefill.percentile(0.95),
            "prefill_full_wall_s": self.prefill_full_wall_s,
            "prefill_suffix_wall_s": self.prefill_suffix_wall_s,
            "failed": self.failed,
            "timed_out": self.timed_out,
            # warm restart (ISSUE 18): requests admitted from a serving-
            # state snapshot, and how long the work was dark
            "restored": self.restored,
            "restore_downtime_p95_s": self._h_restore_downtime.percentile(
                0.95
            ),
            "health": self.health,
            "cursor_high_water": self.cursor_high_water,
            "mean_occupancy": self.mean_occupancy,
            "mean_ttft": _mean(ttfts),
            "max_ttft": max(ttfts) if ttfts else 0.0,
            "mean_queue_wait": _mean(waits),
            "mean_latency": _mean([r["latency"] for r in done]),
            "mean_decode_tokens_per_sec": _mean(
                [r["decode_tokens_per_sec"] for r in done]
            ),
            # SLO-facing percentile families (log-bucketed histograms:
            # exact to the bucket, stable over unbounded streams)
            "ttft_p50_s": self._h_ttft.percentile(0.50),
            "ttft_p95_s": self._h_ttft.percentile(0.95),
            "ttft_p99_s": self._h_ttft.percentile(0.99),
            "tpot_p50_s": self._h_tpot.percentile(0.50),
            "tpot_p95_s": self._h_tpot.percentile(0.95),
            "tpot_p99_s": self._h_tpot.percentile(0.99),
            "queue_wait_p95_s": self._h_queue_wait.percentile(0.95),
            # per-tenant attribution (ISSUE 11): who submitted, who got
            # served, who was shed — plus each tenant's own latency
            # percentiles (labeled histogram families)
            "tenants": self.tenant_snapshot(),
            # device efficiency (ISSUE 12): the compiled-program ledger
            # (compiler-reported cost, dispatch counts, roofline) and the
            # HBM resident accounting — {} when no engine attached them
            "programs": (
                programs.snapshot(analyze=analyze_programs)
                if programs is not None else {}
            ),
            "hbm": hbm.snapshot() if hbm is not None else {},
            # SLO accounting (present only with slo= specs): attainment +
            # goodput, totals and per tenant
            **(
                {"slo": self.slo.snapshot()} if self.slo is not None else {}
            ),
            # speculative serving (ISSUE 9): identical keys to the solo
            # speculative path's registry reporting — all zero without a
            # draft model
            **self.spec.snapshot(),
        }

"""Serving metrics: per-request latency breakdown + engine counters.

Everything is plain host-side accounting (the engine clock is injectable for
deterministic tests) exported as a dict ``snapshot()``; when the engine holds
a ``utils.timeline.Timeline``, per-step occupancy and queue depth also land
on counter tracks next to the prefill/decode duration events, so one Perfetto
view shows the whole scheduling story.

Since ISSUE 8 the counters live in a shared
:class:`~neuronx_distributed_tpu.observability.registry.MetricsRegistry`
(pass one in to co-export serving and trainer metrics from a single
Prometheus surface; ``metrics.registry.prometheus_text()`` is the scrape
payload). The attribute surface is unchanged — ``metrics.steps`` etc. read
through to the registry — and the ``snapshot()`` keys are preserved
bit-for-bit in name and type.

Latency percentiles come from log-bucketed histograms (exact to the bucket,
fixed memory): the prefill p95 no longer reads a 512-sample recent window —
whose value drifted with stream phase on long runs — and new TTFT/TPOT
histograms (``ttft_p50_s``..``tpot_p99_s`` in the snapshot) feed the SLO
scheduling work the ROADMAP names. Recording stays sync-free: every sample
is a host scalar the engine already owned.
"""

from __future__ import annotations

from typing import Dict, Optional

from neuronx_distributed_tpu.observability.registry import MetricsRegistry
from neuronx_distributed_tpu.observability.spec_stats import SpecStats


def _mean(xs):
    return sum(xs) / len(xs) if xs else 0.0


# (attribute, registry metric name, int-valued) — the engine/test-visible
# counter surface, all backed by registry counters
_COUNTERS = (
    ("steps", "serving_steps", True),
    ("chunks", "serving_chunks", True),
    ("prefills", "serving_prefills", True),
    ("decode_tokens", "serving_decode_tokens", True),
    ("completed", "serving_completed", True),
    ("cancelled", "serving_cancelled", True),
    ("preemptions", "serving_preemptions", True),
    ("sheds", "serving_sheds", True),
    ("rejects", "serving_rejects", True),
    ("quarantines", "serving_quarantines", True),
    ("dispatch_retries", "serving_dispatch_retries", True),
    ("recoveries", "serving_recoveries", True),
    ("prefill_failures", "serving_prefill_failures", True),
    ("failed", "serving_failed", True),
    ("timed_out", "serving_timed_out", True),
    ("prefix_hits", "serving_prefix_hits", True),
    ("prefix_misses", "serving_prefix_misses", True),
    ("prefix_tokens_reused", "serving_prefix_tokens_reused", True),
    ("prefix_evictions", "serving_prefix_evictions", True),
    ("prefix_validation_failures", "serving_prefix_validation_failures", True),
    # paged KV (kv_page_size=): pages retired for poison, and pool pages
    # mapped copy-on-write into a hitting slot (zero bytes moved)
    ("page_quarantines", "serving_page_quarantines", True),
    ("prefix_pages_shared", "serving_prefix_pages_shared", True),
    ("occupied_slot_steps", "serving_occupied_slot_steps", True),
    ("prefill_full_wall_s", "serving_prefill_full_wall_s", False),
    ("prefill_suffix_wall_s", "serving_prefill_suffix_wall_s", False),
    ("decode_dispatch_s", "serving_decode_dispatch_s", False),
    ("decode_readback_s", "serving_decode_readback_s", False),
)

_HEALTH_CODES = {"ok": 0, "degraded": 1, "draining": 2, "halted": 3}


class ServingMetrics:
    """Aggregates the engine's request lifecycle events into a registry."""

    def __init__(self, num_slots: int = 0,
                 registry: Optional[MetricsRegistry] = None):
        self.num_slots = num_slots
        if registry is not None and registry.get(_COUNTERS[0][1]) is not None:
            # registries have no instance labels, so a second engine on the
            # same registry would SILENTLY merge its counters into the
            # first's (and last-writer-wins the export gauges). Refuse
            # loudly: one registry per engine; sharing across SUBSYSTEMS
            # (serving_ + train_ prefixes) is the supported pattern, and
            # multi-replica aggregation belongs to the scrape layer
            raise ValueError(
                "registry already holds serving metrics (another "
                "ServingEngine registered into it) — pass a distinct "
                "MetricsRegistry per engine"
            )
        self.registry = registry if registry is not None else MetricsRegistry()
        self._c = {}
        for attr, name, is_int in _COUNTERS:
            self._c[attr] = (self.registry.counter(name), is_int)
        # latency histograms: log-bucketed, fixed memory, quantiles exact
        # to the bucket (observability/registry.py) — prefill feeds the
        # legacy prefill_p95_s key; TTFT/TPOT feed the SLO roadmap item
        self._h_prefill = self.registry.histogram(
            "serving_prefill_latency_s",
            help="wall time of one successful prefill dispatch (s)",
        )
        self._h_ttft = self.registry.histogram(
            "serving_ttft_s", help="submit -> first token (s)"
        )
        self._h_tpot = self.registry.histogram(
            "serving_tpot_s",
            help="per-request mean time per output token after the first (s)",
        )
        self._h_queue_wait = self.registry.histogram(
            "serving_queue_wait_s", help="submit -> first admission (s)"
        )
        self._g_cursor = self.registry.gauge(
            "serving_cursor_high_water", help="highest shared cache cursor seen"
        )
        self._g_health = self.registry.gauge(
            "serving_health", help="0=ok 1=degraded 2=draining 3=halted"
        )
        self._g_health.set_fn(lambda: _HEALTH_CODES.get(self.health, -1))
        # speculative-decoding acceptance stats: the SHARED recorder (solo
        # speculative_generate reports through the same class, so both
        # paths expose identical names/keys); always registered so the
        # snapshot surface is stable whether or not a draft model is bound
        self.spec = SpecStats(self.registry, prefix="spec")
        self.registry.gauge("serving_num_slots").set(num_slots)
        self.health = "ok"  # engine-owned mirror of ServingEngine.health()
        self.cursor_high_water = 0
        # per-request
        self._requests: Dict[int, dict] = {}

    def __getattr__(self, name):
        # counter attributes (``metrics.steps`` etc.) read through to the
        # registry; only consulted when no instance attribute exists
        c = self.__dict__.get("_c")
        if c is not None and name in c:
            counter, is_int = c[name]
            v = counter.value
            return int(v) if is_int else float(v)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}"
        )

    def _inc(self, attr: str, n=1) -> None:
        self._c[attr][0].inc(n)

    # --- request lifecycle --------------------------------------------------

    def record_submit(self, req, now: float) -> None:
        self._requests[req.rid] = {
            "rid": req.rid,
            "prompt_len": int(len(req.prompt)),
            "submit_time": now,
        }

    def record_admit(self, req, now: float) -> None:
        r = self._requests[req.rid]
        # first admission sets the queue wait; re-admissions after preemption
        # keep the original (the request never left the engine's care)
        r.setdefault("admit_time", now)
        if "queue_wait" not in r:
            r["queue_wait"] = now - r["submit_time"]
            self._h_queue_wait.observe(r["queue_wait"])
        self._inc("prefills")

    def record_first_token(self, req, now: float) -> None:
        r = self._requests[req.rid]
        r["first_token_time"] = now
        r["ttft"] = now - r["submit_time"]
        self._h_ttft.observe(r["ttft"])

    def record_finish(self, req, now: float) -> None:
        r = self._requests[req.rid]
        r["finish_time"] = now
        r["latency"] = now - r["submit_time"]
        r["tokens"] = len(req.tokens)
        decode_span = now - r.get("first_token_time", now)
        # tokens after the first are decode-step products
        r["decode_tokens_per_sec"] = (
            (len(req.tokens) - 1) / decode_span if decode_span > 0 else 0.0
        )
        if len(req.tokens) > 1:
            self._h_tpot.observe(decode_span / (len(req.tokens) - 1))
        r["preemptions"] = req.preemptions
        self._inc("completed")

    def record_cancel(self, req, now: float) -> None:
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["cancelled"] = True
        self._inc("cancelled")

    def record_preemption(self, req) -> None:
        self._inc("preemptions")

    # --- fault tolerance ----------------------------------------------------

    def record_shed(self, req, now: float, where: str) -> None:
        """A request timed out — ``where`` is ``"queue"`` (shed before
        prefill) or ``"inflight"`` (deadline hit at a chunk boundary)."""
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["timed_out"] = True
            r["shed_where"] = where
            r["tokens"] = len(req.tokens)
        self._inc("sheds")
        self._inc("timed_out")

    def record_reject(self, queue_depth: int, reason: str) -> None:
        self._inc("rejects")

    def record_quarantine(self, slot: int, rid) -> None:
        self._inc("quarantines")

    def record_dispatch_retry(self) -> None:
        self._inc("dispatch_retries")

    def record_recovery(self, requeued: int) -> None:
        self._inc("recoveries")

    def record_failed(self, req, now: float, kind: str = "engine") -> None:
        """A request the engine failed for cause (``req.error`` has the
        reason): ``kind`` is ``"prefill"`` (OOM-like admission fault) or
        ``"quarantine"`` (poisoned slot under the fail policy)."""
        r = self._requests.get(req.rid)
        if r is not None:
            r["finish_time"] = now
            r["failed"] = True
            r["failed_kind"] = kind
        self._inc("failed")
        if kind == "prefill":
            self._inc("prefill_failures")

    # --- prefix cache -------------------------------------------------------

    def record_prefix_hit(self, matched: int, prompt_len: int) -> None:
        """An admission reused ``matched`` stored prefix tokens of a
        ``prompt_len``-token context (only the tail was prefilled)."""
        self._inc("prefix_hits")
        self._inc("prefix_tokens_reused", matched)

    def record_prefix_pages_shared(self, n: int) -> None:
        """A paged prefix hit mapped ``n`` pool pages copy-on-write into
        the admitted slot's block table (zero KV bytes copied)."""
        self._inc("prefix_pages_shared", n)

    def record_page_quarantine(self, page: int, victims: int) -> None:
        """A poisoned pool page was retired; ``victims`` requests mapping
        it were requeued (page-granular fault domain)."""
        self._inc("page_quarantines")

    def record_prefix_miss(self) -> None:
        self._inc("prefix_misses")

    def record_prefix_eviction(self, n: int = 1) -> None:
        self._inc("prefix_evictions", n)

    def record_prefix_validation_failure(self) -> None:
        """A stored entry failed its reuse-time checksum/shape validation —
        it was evicted and the admission fell back to a full prefill."""
        self._inc("prefix_validation_failures")

    def record_prefill_wall(self, seconds: float, kind: str = "full") -> None:
        """Wall time of one successful prefill dispatch (``kind`` is
        ``"full"`` or ``"suffix"``); feeds the latency histogram (count/
        mean/p95 in :meth:`snapshot`) and the per-kind wall split."""
        self._h_prefill.observe(seconds)
        if kind == "suffix":
            self._inc("prefill_suffix_wall_s", seconds)
        else:
            self._inc("prefill_full_wall_s", seconds)

    # --- engine step --------------------------------------------------------

    def record_decode_step(self, active_slots: int, cursor: int) -> None:
        """Single-step accounting — the chunk-size-1 special case."""
        self.record_decode_chunk(active_slots, 1, cursor, active_slots)

    def record_decode_chunk(
        self,
        tokens: int,
        steps: int,
        cursor: int,
        active_slots: int,
        dispatch_s: float = 0.0,
        readback_s: float = 0.0,
        spec_accepts=None,
        gamma: int = 0,
    ) -> None:
        """One fused decode chunk: ``tokens`` DELIVERED to requests across
        ``steps`` executed scan steps by ``active_slots`` slots held at
        dispatch. Occupancy counts slots HELD, not tokens — a slot frozen
        mid-chunk (early EOS) still owns its cache row until the chunk
        boundary, so it occupies all ``steps``. ``dispatch_s``/
        ``readback_s`` split the wall time around the chunk's single host
        sync.

        Speculative chunks (``steps`` = executed ROUNDS) additionally pass
        ``spec_accepts`` — one accepted-draft length per (live round, slot)
        pair, already host scalars from the chunk's single readback — and
        ``gamma``; the draft/verify split (drafted vs accepted vs wasted
        draft tokens) lands in the shared ``SpecStats`` recorder."""
        self._inc("chunks")
        self._inc("steps", steps)
        self._inc("decode_tokens", tokens)
        self._inc("occupied_slot_steps", active_slots * steps)
        if cursor > self.cursor_high_water:
            self.cursor_high_water = cursor
            self._g_cursor.set(cursor)
        self._inc("decode_dispatch_s", dispatch_s)
        self._inc("decode_readback_s", readback_s)
        if spec_accepts is not None:
            for a in spec_accepts:
                self.spec.record_round(int(a), gamma)

    def record_spec_fallback(self) -> None:
        """A speculative dispatch failed and the chunk was decoded
        non-speculatively instead (streams unaffected)."""
        self.spec.record_fallback()

    # --- export -------------------------------------------------------------

    @property
    def prefill_count(self) -> int:
        """Successful prefill dispatches (full + suffix)."""
        return self._h_prefill.count

    @property
    def prefill_wall_s(self) -> float:
        return float(self._h_prefill.sum)

    @property
    def mean_occupancy(self) -> float:
        """Mean active slots per decode step (≤ num_slots)."""
        return self.occupied_slot_steps / self.steps if self.steps else 0.0

    def request_snapshot(self, rid: int) -> Optional[dict]:
        r = self._requests.get(rid)
        return dict(r) if r is not None else None

    def snapshot(self) -> dict:
        """Plain-dict export (log lines, tests, dashboards). Every key of
        the pre-registry snapshot is preserved in name and type; the
        percentile keys now read bucket-exact histogram quantiles, and the
        ``ttft_*``/``tpot_*`` families are new."""
        done = [r for r in self._requests.values() if "latency" in r]
        ttfts = [r["ttft"] for r in self._requests.values() if "ttft" in r]
        waits = [
            r["queue_wait"] for r in self._requests.values()
            if "queue_wait" in r
        ]
        decode_wall = self.decode_dispatch_s + self.decode_readback_s
        return {
            "num_slots": self.num_slots,
            "steps": self.steps,
            "chunks": self.chunks,
            "decode_dispatch_s": self.decode_dispatch_s,
            "decode_readback_s": self.decode_readback_s,
            "chunk_tokens_per_sec": (
                self.decode_tokens / decode_wall if decode_wall > 0 else 0.0
            ),
            "prefills": self.prefills,
            "decode_tokens": self.decode_tokens,
            "completed": self.completed,
            "cancelled": self.cancelled,
            "preemptions": self.preemptions,
            "sheds": self.sheds,
            "rejects": self.rejects,
            "quarantines": self.quarantines,
            "dispatch_retries": self.dispatch_retries,
            "recoveries": self.recoveries,
            "prefill_failures": self.prefill_failures,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_hit_rate": (
                self.prefix_hits / (self.prefix_hits + self.prefix_misses)
                if self.prefix_hits + self.prefix_misses else 0.0
            ),
            "prefix_tokens_reused": self.prefix_tokens_reused,
            "prefix_evictions": self.prefix_evictions,
            "prefix_validation_failures": self.prefix_validation_failures,
            "prefix_pages_shared": self.prefix_pages_shared,
            "page_quarantines": self.page_quarantines,
            "prefill_count": self.prefill_count,
            "prefill_wall_s": self.prefill_wall_s,
            "prefill_mean_s": self._h_prefill.mean,
            "prefill_p95_s": self._h_prefill.percentile(0.95),
            "prefill_full_wall_s": self.prefill_full_wall_s,
            "prefill_suffix_wall_s": self.prefill_suffix_wall_s,
            "failed": self.failed,
            "timed_out": self.timed_out,
            "health": self.health,
            "cursor_high_water": self.cursor_high_water,
            "mean_occupancy": self.mean_occupancy,
            "mean_ttft": _mean(ttfts),
            "max_ttft": max(ttfts) if ttfts else 0.0,
            "mean_queue_wait": _mean(waits),
            "mean_latency": _mean([r["latency"] for r in done]),
            "mean_decode_tokens_per_sec": _mean(
                [r["decode_tokens_per_sec"] for r in done]
            ),
            # SLO-facing percentile families (log-bucketed histograms:
            # exact to the bucket, stable over unbounded streams)
            "ttft_p50_s": self._h_ttft.percentile(0.50),
            "ttft_p95_s": self._h_ttft.percentile(0.95),
            "ttft_p99_s": self._h_ttft.percentile(0.99),
            "tpot_p50_s": self._h_tpot.percentile(0.50),
            "tpot_p95_s": self._h_tpot.percentile(0.95),
            "tpot_p99_s": self._h_tpot.percentile(0.99),
            "queue_wait_p95_s": self._h_queue_wait.percentile(0.95),
            # speculative serving (ISSUE 9): identical keys to the solo
            # speculative path's registry reporting — all zero without a
            # draft model
            **self.spec.snapshot(),
        }

from neuronx_distributed_tpu.operators.topk import argmax, topk

__all__ = ["topk", "argmax"]

"""Distributed top-k / argmax over a tp-sharded dim (reference:
``operators/topk.py:12``, ``operators/argmax.py:12`` — custom
``torch_neuronx`` XLA ops with a multi-stage tree reduction for tp32/tp64).

TPU formulation: two-stage candidate reduction inside ``shard_map`` — each tp
shard computes its local ``lax.top_k``, candidates (k per shard) are
all-gathered (k·tp values, tiny), and a second local top-k over the gathered
candidates with global-index correction yields the exact result on every
shard. This is the same tree idea as the reference's multi-stage kernel, with
XLA choosing the gather layout. Without a mesh (or tp=1) it degrades to plain
``lax.top_k``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib


def _local_then_global_topk(x, k, axis_name):
    """Inside shard_map: x (..., V_local) → exact global (values, indices)."""
    tp = lax.axis_size(axis_name)
    rank = mesh_lib.compat_axis_index(axis_name)
    v_loc = x.shape[-1]
    vals, idx = lax.top_k(x, k)  # local candidates
    idx = idx + rank * v_loc  # globalize indices
    # gather candidates from every shard: (..., tp*k)
    vals_g = lax.all_gather(vals, axis_name, axis=x.ndim - 1, tiled=True)
    idx_g = lax.all_gather(idx, axis_name, axis=x.ndim - 1, tiled=True)
    top_vals, cand_pos = lax.top_k(vals_g, k)
    top_idx = jnp.take_along_axis(idx_g, cand_pos, axis=-1)
    del tp
    return top_vals, top_idx


def topk(
    x: jax.Array, k: int, dim: int = -1, axis_name: str = mesh_lib.TP_AXIS
) -> Tuple[jax.Array, jax.Array]:
    """Exact top-k of a (possibly tp-sharded) tensor along ``dim``.
    Returns replicated ``(values, global_indices)`` (reference topk:12)."""
    dim = dim % x.ndim
    if dim != x.ndim - 1:
        x = jnp.moveaxis(x, dim, -1)
    if (
        not mesh_lib.model_parallel_is_initialized()
        or mesh_lib.get_mesh().shape[axis_name] == 1
    ):
        vals, idx = lax.top_k(x, k)
    else:
        mesh = mesh_lib.get_mesh()
        if x.shape[-1] % mesh.shape[axis_name] != 0:
            vals, idx = lax.top_k(x, k)  # not shardable → plain path
        else:
            in_spec = P(*([None] * (x.ndim - 1)), axis_name)
            out_spec = P(*([None] * x.ndim))
            vals, idx = mesh_lib.manual_shard_map(
                lambda t: _local_then_global_topk(t, k, axis_name),
                in_specs=(in_spec,),
                out_specs=(out_spec, out_spec),
            )(x)
    if dim != x.ndim - 1:
        vals = jnp.moveaxis(vals, -1, dim)
        idx = jnp.moveaxis(idx, -1, dim)
    return vals, idx


def argmax(
    x: jax.Array, dim: int = -1, keepdim: bool = False, axis_name: str = mesh_lib.TP_AXIS
) -> jax.Array:
    """Exact argmax over a (possibly tp-sharded) dim with global indices
    (reference argmax:12)."""
    _, idx = topk(x, 1, dim=dim, axis_name=axis_name)
    if not keepdim:
        idx = jnp.squeeze(idx, axis=dim % x.ndim)
    return idx

"""ZeRO-1 optimizer-state sharding (reference: ``optimizer/
zero_redundancy_optimizer.py`` ``NeuronZero1Optimizer:29``).

The reference wraps torch_xla's ZeroRedundancyOptimizer: reduce-scatter grads
over the DP(×CP) sharding groups, step a local shard, all-gather params. On
TPU the same dataflow falls out of sharding annotations: give each optimizer
moment (mu/nu) a PartitionSpec that extends its param's spec by sharding the
largest still-unsharded dimension over the zero-1 axes (dp, cp), and jit the
train step with those as in/out shardings. XLA's partitioner then turns the DP
grad all-reduce into reduce-scatter + the param update's all-gather — exactly
the ZeRO-1 schedule, chosen per-tensor, overlapping with compute.

``zero1_partition_spec`` is the policy; ``zero1_shardings_for_opt_state``
applies it to an arbitrary optax state pytree by matching param paths (mu/nu
subtrees carry the same relative paths as the param tree).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)


def zero1_partition_spec(
    param_spec: P, shape: Tuple[int, ...], mesh=None, axes: Optional[Tuple[str, ...]] = None
) -> P:
    """Extend ``param_spec`` by sharding the largest unsharded, divisible dim
    over the zero-1 axes (dp, cp — reference get_zero1_sharding_groups,
    parallel_state.py:1579). Falls back to the param spec when nothing fits."""
    mesh = mesh or mesh_lib.get_mesh()
    axes = axes or mesh_lib.zero1_sharding_axes()
    # axes the param itself is already sharded over (e.g. expert weights on
    # "ep") must not appear twice in the extended spec
    used = set()
    for entry in param_spec:
        if isinstance(entry, str):
            used.add(entry)
        elif isinstance(entry, (tuple, list)):
            used.update(entry)
    axes = tuple(a for a in axes if a in mesh.shape and a not in used)
    n = int(np.prod([mesh.shape[a] for a in axes]))
    if n == 1 or not shape:
        return param_spec
    entries = list(param_spec) + [None] * (len(shape) - len(param_spec))
    # largest-first so the big dim (e.g. vocab or ffn) takes the dp shard
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if entries[i] is None and shape[i] % n == 0:
            entries[i] = axes if len(axes) > 1 else axes[0]
            return P(*entries)
    return param_spec


def _flatten_with_path(tree):
    return jax.tree_util.tree_flatten_with_path(tree)


def _path_suffix_key(path) -> Tuple[str, ...]:
    from neuronx_distributed_tpu.utils.tree import path_keys

    return path_keys(path)


def zero1_shardings_for_opt_state(
    opt_state_shapes: Any,
    params: Any,
    param_specs: Any,
    mesh=None,
    enabled: bool = True,
    axes: Optional[Tuple[str, ...]] = None,
) -> Any:
    """Build a NamedSharding pytree for an optax state.

    ``opt_state_shapes``: ``jax.eval_shape(optimizer.init, params)``.
    Leaves whose path-suffix matches a param path get that param's zero-1 spec;
    everything else (step counts, scalars) is replicated. With
    ``enabled=False`` moments get the plain param spec (non-ZeRO baseline).
    """
    mesh = mesh or mesh_lib.get_mesh()
    if enabled and mesh.shape.get(mesh_lib.PP_AXIS, 1) > 1:
        # Known XLA SPMD-partitioner CHECK crash (spmd_partitioner_util.cc:495,
        # jaxlib 0.9) when optimizer moments carry pp+dp mixed shardings fed by
        # grads from a partial-manual shard_map. Fall back to param-sharded
        # optimizer state under pipeline parallelism until the explicit
        # shard_map ZeRO-1 path lands.
        logger.warning(
            "zero1 optimizer-state sharding disabled under pipeline parallelism "
            "(XLA partitioner limitation); optimizer state uses param shardings"
        )
        enabled = False
    param_leaves, _ = _flatten_with_path(params)
    spec_leaves, _ = _flatten_with_path(param_specs)
    by_suffix = {}
    for (ppath, pleaf), (_, sleaf) in zip(param_leaves, spec_leaves):
        by_suffix[_path_suffix_key(ppath)] = (pleaf.shape, sleaf)

    def resolve(path, leaf):
        key = _path_suffix_key(path)
        # match the longest param-path suffix embedded in the opt-state path
        for start in range(len(key)):
            suffix = key[start:]
            if suffix in by_suffix:
                shape, spec = by_suffix[suffix]
                if tuple(leaf.shape) == tuple(shape):
                    if enabled:
                        return NamedSharding(
                            mesh, zero1_partition_spec(spec, shape, mesh, axes=axes)
                        )
                    return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    flat, treedef = _flatten_with_path(opt_state_shapes)
    return jax.tree_util.tree_unflatten(treedef, [resolve(p, l) for p, l in flat])

"""ZeRO-1 optimizer-state sharding (reference: ``optimizer/
zero_redundancy_optimizer.py`` ``NeuronZero1Optimizer:29``).

The reference wraps torch_xla's ZeroRedundancyOptimizer: reduce-scatter grads
over the DP(×CP) sharding groups, step a local shard, all-gather params. On
TPU the same dataflow falls out of sharding annotations: give each optimizer
moment (mu/nu) a PartitionSpec that extends its param's spec by sharding the
largest still-unsharded dimension over the zero-1 axes (dp, cp), and jit the
train step with those as in/out shardings. XLA's partitioner then turns the DP
grad all-reduce into reduce-scatter + the param update's all-gather — exactly
the ZeRO-1 schedule, chosen per-tensor, overlapping with compute.

``zero1_partition_spec`` is the policy; ``zero1_shardings_for_opt_state``
applies it to an arbitrary optax state pytree by matching param paths (mu/nu
subtrees carry the same relative paths as the param tree).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from neuronx_distributed_tpu.parallel import mesh as mesh_lib
from neuronx_distributed_tpu.utils.logger import get_logger

logger = get_logger(__name__)

_is_spec = lambda s: isinstance(s, P)  # noqa: E731


def zero1_partition_spec(
    param_spec: P, shape: Tuple[int, ...], mesh=None, axes: Optional[Tuple[str, ...]] = None
) -> P:
    """Extend ``param_spec`` by sharding the largest unsharded, divisible dim
    over the zero-1 axes (dp, cp — reference get_zero1_sharding_groups,
    parallel_state.py:1579). Falls back to the param spec when nothing fits."""
    mesh = mesh or mesh_lib.get_mesh()
    axes = axes or mesh_lib.zero1_sharding_axes()
    # axes the param itself is already sharded over (e.g. expert weights on
    # "ep") must not appear twice in the extended spec
    used = set()
    for entry in param_spec:
        if isinstance(entry, str):
            used.add(entry)
        elif isinstance(entry, (tuple, list)):
            used.update(entry)
    axes = tuple(a for a in axes if a in mesh.shape and a not in used)
    n = int(np.prod([mesh.shape[a] for a in axes]))
    if n == 1 or not shape:
        return param_spec
    entries = list(param_spec) + [None] * (len(shape) - len(param_spec))
    # largest-first so the big dim (e.g. vocab or ffn) takes the dp shard
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if entries[i] is None and shape[i] % n == 0:
            entries[i] = axes if len(axes) > 1 else axes[0]
            return P(*entries)
    return param_spec


def _flatten_with_path(tree):
    return jax.tree_util.tree_flatten_with_path(tree)


def _path_suffix_key(path) -> Tuple[str, ...]:
    from neuronx_distributed_tpu.utils.tree import path_keys

    return path_keys(path)


def zero1_shardings_for_opt_state(
    opt_state_shapes: Any,
    params: Any,
    param_specs: Any,
    mesh=None,
    enabled: bool = True,
    axes: Optional[Tuple[str, ...]] = None,
) -> Any:
    """Build a NamedSharding pytree for an optax state.

    ``opt_state_shapes``: ``jax.eval_shape(optimizer.init, params)``.
    Leaves whose path-suffix matches a param path get that param's zero-1 spec;
    everything else (step counts, scalars) is replicated. With
    ``enabled=False`` moments get the plain param spec (non-ZeRO baseline).
    """
    mesh = mesh or mesh_lib.get_mesh()
    param_leaves, _ = _flatten_with_path(params)
    spec_leaves, _ = _flatten_with_path(param_specs)
    by_suffix = {}
    for (ppath, pleaf), (_, sleaf) in zip(param_leaves, spec_leaves):
        by_suffix[_path_suffix_key(ppath)] = (pleaf.shape, sleaf)

    def resolve(path, leaf):
        key = _path_suffix_key(path)
        # match the longest param-path suffix embedded in the opt-state path
        for start in range(len(key)):
            suffix = key[start:]
            if suffix in by_suffix:
                shape, spec = by_suffix[suffix]
                if tuple(leaf.shape) == tuple(shape):
                    if enabled:
                        return NamedSharding(
                            mesh, zero1_partition_spec(spec, shape, mesh, axes=axes)
                        )
                    return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    flat, treedef = _flatten_with_path(opt_state_shapes)
    return jax.tree_util.tree_unflatten(treedef, [resolve(p, l) for p, l in flat])


# --- explicit ZeRO-1 update (the reference's shard-step-allgather loop) -------


def opt_state_is_zero1_sharded(opt_state_shardings) -> bool:
    """True when any optimizer-state leaf carries a zero-1-extended spec.

    Param specs never use the edp or cp axes (parameters are replicated over
    data/context parallelism — there is no FSDP param sharding here), so their
    presence in an optimizer-state spec is exactly the zero-1 extension made
    by :func:`zero1_partition_spec`."""
    for sh in jax.tree.leaves(opt_state_shardings):
        for entry in sh.spec:
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            if mesh_lib.EDP_AXIS in axes or mesh_lib.CP_AXIS in axes:
                return True
    return False


def _zero1_added_dim(param_spec: P, z1_spec: P):
    """(dim, axes) added by the zero-1 extension, or None when specs agree."""
    pe = list(param_spec)
    ze = list(z1_spec)
    if pe == ze:
        return None
    pe = pe + [None] * (len(ze) - len(pe))
    for i, (a, b) in enumerate(zip(pe, ze)):
        if a != b:
            return i, tuple(b) if isinstance(b, (tuple, list)) else (b,)
    return None


def build_explicit_zero1_update(optimizer, params_shardings, opt_state_shardings):
    """Return ``update_fn(grads, opt_state, params) -> (new_params, new_opt_state)``
    running the reference's explicit ZeRO-1 dataflow
    (zero_redundancy_optimizer.py:29 — reduce-scatter grads over the sharding
    groups, step a local shard, all-gather params) inside one fully-manual
    ``shard_map``.

    This exists because the GSPMD formulation (zero-1 specs as plain
    out-shardings) hits an XLA SPMD-partitioner CHECK crash
    (spmd_partitioner_util.cc:495, jaxlib 0.9) when the grads feeding the
    update come out of a partial-manual shard_map (the pipeline engine).
    Inside a fully-manual region the partitioner never sees the mixed
    pp+dp shardings: grads arrive already DP-all-reduced (autodiff inserted
    the psum), each device slices its zero-1 shard (XLA's
    ReduceScatterCreator pass folds all-reduce + partition-id slice into a
    reduce-scatter), steps optax on the shard, and all-gathers the updated
    params.

    CONSTRAINT: the optimizer's update must be elementwise per-tensor (adam/
    adamw/sgd/lion...). Optimizers that reduce across a whole tensor or the
    whole tree (adafactor's factored moments, a chained clip_by_global_norm)
    would compute those reductions over the local zero-1 shard and silently
    change the math — use the GSPMD path (pp=1) or zero1=False for those."""
    logger.warning(
        "explicit ZeRO-1 update engaged (pp>1): optimizer update must be "
        "elementwise per-tensor (adam/adamw/sgd are; adafactor and "
        "global-norm-chained transforms are NOT)"
    )
    mesh = mesh_lib.get_mesh()
    param_specs = jax.tree.map(lambda s: s.spec, params_shardings)
    opt_specs = jax.tree.map(lambda s: s.spec, opt_state_shardings)

    axis_order = list(mesh.axis_names)

    def update_fn(grads, opt_state, params):
        # Per-leaf zero-1 spec, recomputed with the same policy that built
        # opt_state_shardings — shapes come from the (global) tracers, so this
        # must run OUTSIDE the shard_map.
        z1_specs = jax.tree.map(
            lambda spec, g: zero1_partition_spec(spec, g.shape, mesh),
            param_specs,
            grads,
            is_leaf=_is_spec,
        )

        # Per-axis rank, WITHOUT lax.axis_index: an iota sharded over axis
        # ``a`` arrives in-region as the 1-element slice holding this rank's
        # index. axis_index inside this shard_map lowers into a nested
        # manual_computation that re-binds already-manual axes ("operates on
        # axis 'pp' which is already bound" — cp>1 × pp>1, round 5).
        rank_arrays = tuple(
            jax.numpy.arange(mesh.shape[a], dtype=jax.numpy.int32)
            for a in axis_order
        )
        rank_specs = tuple(P(a) for a in axis_order)

        def _slice(ranks, spec, z1, leaf):
            info = _zero1_added_dim(spec, z1)
            if info is None:
                return leaf
            dim, axes = info
            n = int(np.prod([mesh.shape[a] for a in axes]))
            idx = jax.numpy.zeros((), jax.numpy.int32)
            for a in axes:  # row-major over axes, matching all_gather order
                idx = idx * mesh.shape[a] + ranks[axis_order.index(a)][0]
            size = leaf.shape[dim] // n
            return lax.dynamic_slice_in_dim(leaf, idx * size, size, dim)

        def _gather(spec, z1, leaf):
            info = _zero1_added_dim(spec, z1)
            if info is None:
                return leaf
            dim, axes = info
            return lax.all_gather(leaf, axes, axis=dim, tiled=True)

        def inner(ranks, g, o, p):
            from functools import partial as _partial

            sl = _partial(_slice, ranks)
            g_shard = jax.tree.map(sl, param_specs, z1_specs, g, is_leaf=_is_spec)
            p_shard = jax.tree.map(sl, param_specs, z1_specs, p, is_leaf=_is_spec)
            import optax

            updates, new_o = optimizer.update(g_shard, o, p_shard)
            new_p_shard = optax.apply_updates(p_shard, updates)
            new_p = jax.tree.map(
                _gather, param_specs, z1_specs, new_p_shard, is_leaf=_is_spec
            )
            return new_p, new_o

        # jit wrapper: the eager shard_map impl cannot execute fully-manual
        # specs over the concrete mesh, and under the outer jitted step_fn
        # this traces once and inlines (same rule as mesh.manual_shard_map).
        # Rank indices enter as sharded iotas — NOT lax.axis_index, whose
        # in-region lowering re-binds already-manual axes (see rank_arrays).
        ctx_mesh = mesh_lib.ctx_abstract_mesh()
        fn = jax.jit(mesh_lib.compat_shard_map(
            inner,
            mesh=mesh if ctx_mesh.empty else ctx_mesh,
            in_specs=(rank_specs, param_specs, opt_specs, param_specs),
            out_specs=(param_specs, opt_specs),
            check_vma=False,
        ))
        return fn(rank_arrays, grads, opt_state, params)

    return update_fn

from neuronx_distributed_tpu.optim.zero1 import (
    zero1_partition_spec,
    zero1_shardings_for_opt_state,
)

__all__ = ["zero1_partition_spec", "zero1_shardings_for_opt_state"]

"""Benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
computed against a hardware-grounded target: 40% MFU at the chip's peak bf16
FLOPs (v5e ≈ 197 TFLOP/s) using the standard 6·N·tokens/step transformer FLOP
count — i.e. vs_baseline = achieved_MFU / 0.40. >1.0 beats the target.
"""

import json
import time

import jax
import jax.numpy as jnp


def peak_flops_per_chip() -> float:
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def main():
    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.parallel import mesh as mesh_lib
    from neuronx_distributed_tpu.trainer import (
        OptimizerConfig,
        build_train_step,
        create_train_state,
        make_optimizer,
        shard_batch,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    mesh_lib.destroy_model_parallel()
    mesh_lib.initialize_model_parallel(tensor_model_parallel_size=1)

    # Llama-2-7B layer geometry, depth scaled to single-chip HBM (the
    # reference integration-test trick: full width, few layers).
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_layers=2 if on_tpu else 1,
        num_heads=32,
        num_kv_heads=32,
        max_seq_len=2048,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        remat=True,
        scan_layers=False,
    )
    batch, seq = (1, 2048) if on_tpu else (1, 128)

    model = LlamaForCausalLM(cfg)
    optimizer = make_optimizer(OptimizerConfig(zero1=False))
    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)

    state, p_sh, s_sh = create_train_state(model, optimizer, key, ids, zero1=False)
    step = build_train_step(model, optimizer, p_sh, s_sh)
    data = shard_batch({"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)})

    # params for FLOP count
    n_params = sum(p.size for p in jax.tree.leaves(state.params))

    # warmup (compile). NOTE: on the axon TPU relay block_until_ready does not
    # actually wait for device completion — a host readback (float()) is the
    # only reliable sync, so timing uses a two-point slope that cancels the
    # fixed readback RTT.
    for _ in range(2):
        state, metrics = step(state, data)
    _ = float(metrics["loss"])

    def timed(iters):
        nonlocal state
        t0 = time.perf_counter()
        m = None
        for _ in range(iters):
            state, m = step(state, data)
        _ = float(m["loss"])  # force full pipeline completion
        return time.perf_counter() - t0

    n1, n2 = (3, 13) if on_tpu else (1, 4)
    t1 = timed(n1)
    t2 = timed(n2)
    dt = (t2 - t1) / (n2 - n1)
    if dt <= 0:  # fall back if noise dominates
        dt = t2 / n2

    tokens = batch * seq
    tokens_per_sec = tokens / dt
    flops_per_step = 6.0 * n_params * tokens  # fwd+bwd transformer estimate
    mfu = (flops_per_step / dt) / peak_flops_per_chip()
    target_mfu = 0.40
    print(
        json.dumps(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 2),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / target_mfu, 4),
                "extras": {
                    "mfu": round(mfu, 4),
                    "n_params": int(n_params),
                    "step_time_s": round(dt, 4),
                    "layers": cfg.num_layers,
                    "platform": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    main()

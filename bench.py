"""Benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
computed against a hardware-grounded target: 40% MFU at the chip's peak bf16
FLOPs (v5e ≈ 197 TFLOP/s) — i.e. vs_baseline = achieved_MFU / 0.40. >1.0
beats the target.

FLOP accounting (round-3 correction, VERDICT.md weak #2): the headline MFU is
the *corrected* one —

    flops = 6 · (N − N_embed_table) · tokens   (input embedding is a lookup,
                                                not a matmul; lm_head counts)
          + 6 · L · B · S² · H                 (causal QKᵀ+AV fwd+bwd: the
                                                flash kernel computes only the
                                                lower triangle, so half of the
                                                full 12·L·B·S²·H)

both the raw 6·N number and every component are in ``extras`` so the MFU can
be recomputed from the artifact alone.

A second, parallelism-exercising measurement runs on an 8-device virtual CPU
mesh (pp=2×tp=2×dp=2): per-step wall time of the explicit-1F1B engine vs the
GPipe scan engine plus their XLA temp-allocation sizes, logged under
``extras.parallel_proxy`` (VERDICT.md weak #3 — the single-chip number alone
cannot regress if sharded paths get slow).

Round-2 hardening (kept): the measurement runs in child processes with
bounded timeouts and retries; backend-init failures emit a parseable JSON
error line instead of a traceback. The child forces ``attention_impl="flash"``
on TPU so the Pallas kernel demonstrably compiles under Mosaic.
"""

import json
import os
import subprocess
import sys
import time

# Equal per-attempt budgets: a timed-out compile writes nothing to the cache,
# so the retry needs as much time as the first try.
ATTEMPT_TIMEOUTS = (600, 600)


def peak_flops_per_chip(dev) -> float:
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def child() -> None:
    """The actual measurement. Prints the one JSON line on success; on
    failure prints an error JSON (rc stays 0 — the parent decides whether to
    retry based on the ``retryable`` flag)."""
    import jax

    # The axon sitecustomize force-selects the TPU platform regardless of the
    # JAX_PLATFORMS env var; a post-import config update is the only override
    # that sticks (same trick as tests/conftest.py). Used for CPU smoke tests.
    forced = os.environ.get("BENCH_FORCE_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    # Persistent compilation cache: a retried attempt (or a rerun in the same
    # round) skips the 20-40 s first compile.
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    try:
        devs = jax.devices()
    except Exception as e:  # backend init failed — retryable
        _emit(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"backend init failed: {type(e).__name__}: {str(e)[:400]}",
                "retryable": True,
            }
        )
        return

    try:
        _measure(devs)
    except Exception as e:
        _emit(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {str(e)[:400]}",
                "retryable": False,
                "extras": {"platform": devs[0].platform},
            }
        )


def _measure(devs) -> None:
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.parallel import mesh as mesh_lib
    from neuronx_distributed_tpu.trainer import (
        OptimizerConfig,
        build_train_step,
        create_train_state,
        make_optimizer,
        shard_batch,
    )

    on_tpu = devs[0].platform == "tpu"
    mesh_lib.destroy_model_parallel()
    mesh_lib.initialize_model_parallel(tensor_model_parallel_size=1)

    # Llama-2-7B layer geometry, depth scaled to single-chip HBM (the
    # reference integration-test trick: full width, few layers).
    # remat=False: at 2 layers the activations fit HBM comfortably and
    # rematerialization's ~1/3 extra forward FLOPs cost 12% throughput
    # (measured r3: 24.2k → 27.3k tok/s); batch=4 amortizes the weight-grad
    # matmuls further (→ 35.5k tok/s; batch=8 adds only 3% more at 2× the
    # step latency, past the knee).
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_layers=2 if on_tpu else 1,
        num_heads=32,
        num_kv_heads=32,
        max_seq_len=2048,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        remat=False,
        scan_layers=False,
    )
    batch, seq = (4, 2048) if on_tpu else (1, 128)

    # Force the Pallas flash kernel on TPU (compiled by Mosaic — no interpret
    # fallback); XLA einsum path elsewhere.
    attention_impl = "flash" if on_tpu else "xla"
    model = LlamaForCausalLM(cfg, attention_impl=attention_impl)
    optimizer = make_optimizer(OptimizerConfig(zero1=False))
    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)

    state, p_sh, s_sh = create_train_state(model, optimizer, key, ids, zero1=False)
    step = build_train_step(model, optimizer, p_sh, s_sh)
    data = shard_batch({"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)})

    n_params = sum(p.size for p in jax.tree.leaves(state.params))
    # input embedding table does a lookup, not a matmul — exclude from the
    # 6·N count (the lm_head, a real matmul, stays); VERDICT.md round-2 weak #2
    embed_params = cfg.vocab_size * cfg.hidden_size

    # warmup (compile). NOTE: on the axon TPU relay block_until_ready does not
    # actually wait for device completion — a host readback (float()) is the
    # only reliable sync, so timing uses a two-point slope that cancels the
    # fixed readback RTT.
    for _ in range(2):
        state, metrics = step(state, data)
    _ = float(metrics["loss"])

    def timed(iters):
        nonlocal state
        t0 = time.perf_counter()
        m = None
        for _ in range(iters):
            state, m = step(state, data)
        _ = float(m["loss"])  # force full pipeline completion
        return time.perf_counter() - t0

    n1, n2 = (3, 13) if on_tpu else (1, 4)
    t1 = timed(n1)
    t2 = timed(n2)
    dt = (t2 - t1) / (n2 - n1)
    if dt <= 0:  # fall back if noise dominates
        dt = t2 / n2

    tokens = batch * seq
    tokens_per_sec = tokens / dt
    peak = peak_flops_per_chip(devs[0])
    flops_raw = 6.0 * n_params * tokens
    flops_matmul = 6.0 * (n_params - embed_params) * tokens
    # causal attention (QK^T + AV), fwd+bwd = 3× fwd; the flash kernel only
    # computes the lower triangle, so the honest hardware count is half of
    # the full 12·L·B·S²·H
    flops_attn = 6.0 * cfg.num_layers * batch * seq * seq * cfg.hidden_size
    mfu_raw = (flops_raw / dt) / peak
    mfu = ((flops_matmul + flops_attn) / dt) / peak
    target_mfu = 0.40
    _emit(
        {
            "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 2),
            "unit": "tokens/s",
            "vs_baseline": round(mfu / target_mfu, 4),
            "extras": {
                "mfu": round(mfu, 4),
                "mfu_raw_6n": round(mfu_raw, 4),
                "flops_matmul_per_step": flops_matmul,
                "flops_attn_per_step": flops_attn,
                "embed_params_excluded": int(embed_params),
                "peak_flops": peak,
                "n_params": int(n_params),
                "step_time_s": round(dt, 4),
                "batch": batch,
                "seq": seq,
                "layers": cfg.num_layers,
                "platform": devs[0].platform,
                "attention_impl": attention_impl,
            },
        }
    )


def child_parallel() -> None:
    """Parallelism proxy on an 8-device virtual CPU mesh: step time + XLA
    temp-allocation of the explicit-1F1B engine vs the GPipe scan engine at
    pp=2×tp=2×dp=2 with ZeRO-1 + SP. Emits one JSON line merged by the parent
    into ``extras.parallel_proxy``."""
    from neuronx_distributed_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(8)
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.parallel import mesh as mesh_lib
    from neuronx_distributed_tpu.pipeline.llama import LlamaPipelineAdapter
    from neuronx_distributed_tpu.pipeline.model import (
        microbatch,
        shard_microbatched_batch,
    )
    from neuronx_distributed_tpu.trainer import OptimizerConfig, make_optimizer

    cfg = LlamaConfig(
        vocab_size=512,
        hidden_size=256,
        intermediate_size=704,
        num_layers=4,
        num_heads=8,
        num_kv_heads=4,
        max_seq_len=128,
        # fp32: the CPU backend's AllReducePromotion pass CHECK-crashes on
        # bf16 all-reduces ("Invalid binary instruction opcode copy"); the
        # proxy measures relative engine cost, dtype is immaterial
        dtype=jnp.float32,
        param_dtype=jnp.float32,
        remat=False,
        scan_layers=True,
        sequence_parallel=True,
    )
    M = 8
    mesh_lib.destroy_model_parallel()
    mesh_lib.initialize_model_parallel(
        tensor_model_parallel_size=2, pipeline_model_parallel_size=2
    )
    dp = mesh_lib.get_data_parallel_size()
    model = LlamaForCausalLM(cfg, attention_impl="xla")
    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (M * dp, 64), 0, cfg.vocab_size)
    batch = shard_microbatched_batch(
        microbatch({"input_ids": ids, "labels": jnp.roll(ids, -1, 1)}, M)
    )

    out = {}
    for sched in ("1f1b", "interleaved", "gpipe"):
        adapter = LlamaPipelineAdapter(
            config=cfg, num_microbatches=M, attention_impl="xla", schedule=sched,
            num_chunks=2 if sched == "interleaved" else 1,
        )
        state, step, _engine = adapter.build_state_and_step(
            model, make_optimizer(OptimizerConfig()), key, ids
        )
        # temp-allocation evidence via compiled memory analysis
        lowered = step.lower(state, batch)
        compiled = lowered.compile()
        try:
            temp_bytes = int(compiled.memory_analysis().temp_size_in_bytes)
        except Exception:
            temp_bytes = -1
        state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        t0 = time.perf_counter()
        iters = 3
        for _ in range(iters):
            state, metrics = step(state, batch)
        _ = float(metrics["loss"])
        out[sched] = {
            "step_time_s": round((time.perf_counter() - t0) / iters, 4),
            "temp_alloc_bytes": temp_bytes,
            "loss": round(float(metrics["loss"]), 4),
        }
    _emit(
        {
            "metric": "parallel_proxy",
            "mesh": "cpu pp=2 tp=2 dp=2 sp=on zero1=on",
            "microbatches": M,
            "schedules": out,
        }
    )


def _parse_result(stdout: str):
    """Last stdout line that parses as a JSON object with a 'metric' key."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None


def _run_parallel_proxy():
    """Run the CPU-mesh 1F1B-vs-GPipe proxy child; returns the proxy dict, or
    a dict with an 'error' key on failure (the proxy augments the headline
    metric, it must never sink it)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child-parallel"],
            capture_output=True,
            text=True,
            timeout=420,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"error": "parallel proxy timed out"}
    result = _parse_result(proc.stdout)
    if result is None or result.get("metric") != "parallel_proxy":
        tail = (proc.stderr or proc.stdout or "").strip()[-300:]
        return {"error": f"parallel proxy failed: {tail}"}
    result.pop("metric", None)
    return result


def main() -> None:
    errors = []
    # A successful headline result is stashed here so that a driver SIGTERM
    # during the (optional, slow) parallel proxy still emits the real TPU
    # measurement instead of discarding it.
    headline = {}
    # If the driver kills the harness mid-retry (its outer budget may be
    # shorter than ours), still flush a parseable error JSON on the way out.
    import signal

    def _on_term(signum, frame):
        if headline:
            result = dict(headline)
            result.setdefault("extras", {})["parallel_proxy"] = {
                "error": f"killed by signal {signum} during proxy"
            }
            _emit(result)
        else:
            _emit(
                {
                    "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                    "value": 0,
                    "unit": "tokens/s",
                    "vs_baseline": 0.0,
                    "error": "; ".join(
                        errors + [f"killed by signal {signum} mid-attempt"]
                    ),
                }
            )
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    for attempt, timeout_s in enumerate(ATTEMPT_TIMEOUTS, 1):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timed out after {timeout_s}s (backend hang)")
            continue
        result = _parse_result(proc.stdout)
        if result is None:
            tail = (proc.stderr or proc.stdout or "").strip()[-400:]
            errors.append(f"attempt {attempt}: rc={proc.returncode}, no JSON: {tail}")
            continue
        if "error" in result and result.get("retryable") and attempt < len(ATTEMPT_TIMEOUTS):
            errors.append(f"attempt {attempt}: {result['error']}")
            continue
        if "error" in result:
            errors.append(f"attempt {attempt}: {result['error']}")
            result["error"] = "; ".join(errors)
            result.pop("retryable", None)
        headline.update(result)
        if "error" not in result:
            # only augment a successful headline — a dead bench should not
            # spend minutes compiling the CPU proxy before reporting
            result.setdefault("extras", {})["parallel_proxy"] = _run_parallel_proxy()
        print(json.dumps(result), flush=True)
        return
    _emit(
        {
            "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "; ".join(errors) or "no attempt produced output",
        }
    )


if __name__ == "__main__":
    if "--child-parallel" in sys.argv:
        child_parallel()
    elif "--child" in sys.argv:
        child()
    else:
        main()

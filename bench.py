"""Benchmark: Llama training-step throughput on the local chip(s).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
computed against a hardware-grounded target: 40% MFU at the chip's peak bf16
FLOPs (v5e ≈ 197 TFLOP/s) using the standard 6·N·tokens/step transformer FLOP
count — i.e. vs_baseline = achieved_MFU / 0.40. >1.0 beats the target.

Round-2 hardening (VERDICT.md "What's weak" #1): round 1 died with rc=1 in
``jax.devices()`` — a TPU backend-init error with no fallback, wasting the
round's only chip access.  The bench now runs as a parent harness that spawns
the real measurement in a child process with a bounded timeout and retries
(backend-init hangs/UNAVAILABLE errors are transient on the tunneled axon
backend); if every attempt fails it emits a parseable JSON line with an
``error`` field instead of a traceback.  The child forces
``attention_impl="flash"`` on TPU so the Pallas kernel demonstrably compiles
under Mosaic (round 1 never executed it on hardware).
"""

import json
import os
import subprocess
import sys
import time

# Equal per-attempt budgets: a timed-out compile writes nothing to the cache,
# so the retry needs as much time as the first try.
ATTEMPT_TIMEOUTS = (600, 600)


def peak_flops_per_chip(dev) -> float:
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # conservative default


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def child() -> None:
    """The actual measurement. Prints the one JSON line on success; on
    failure prints an error JSON (rc stays 0 — the parent decides whether to
    retry based on the ``retryable`` flag)."""
    import jax

    # The axon sitecustomize force-selects the TPU platform regardless of the
    # JAX_PLATFORMS env var; a post-import config update is the only override
    # that sticks (same trick as tests/conftest.py). Used for CPU smoke tests.
    forced = os.environ.get("BENCH_FORCE_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    # Persistent compilation cache: a retried attempt (or a rerun in the same
    # round) skips the 20-40 s first compile.
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    try:
        devs = jax.devices()
    except Exception as e:  # backend init failed — retryable
        _emit(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"backend init failed: {type(e).__name__}: {str(e)[:400]}",
                "retryable": True,
            }
        )
        return

    try:
        _measure(devs)
    except Exception as e:
        _emit(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {str(e)[:400]}",
                "retryable": False,
                "extras": {"platform": devs[0].platform},
            }
        )


def _measure(devs) -> None:
    import jax
    import jax.numpy as jnp

    from neuronx_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from neuronx_distributed_tpu.parallel import mesh as mesh_lib
    from neuronx_distributed_tpu.trainer import (
        OptimizerConfig,
        build_train_step,
        create_train_state,
        make_optimizer,
        shard_batch,
    )

    on_tpu = devs[0].platform == "tpu"
    mesh_lib.destroy_model_parallel()
    mesh_lib.initialize_model_parallel(tensor_model_parallel_size=1)

    # Llama-2-7B layer geometry, depth scaled to single-chip HBM (the
    # reference integration-test trick: full width, few layers).
    cfg = LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_layers=2 if on_tpu else 1,
        num_heads=32,
        num_kv_heads=32,
        max_seq_len=2048,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        remat=True,
        scan_layers=False,
    )
    batch, seq = (1, 2048) if on_tpu else (1, 128)

    # Force the Pallas flash kernel on TPU (compiled by Mosaic — no interpret
    # fallback); XLA einsum path elsewhere.
    attention_impl = "flash" if on_tpu else "xla"
    model = LlamaForCausalLM(cfg, attention_impl=attention_impl)
    optimizer = make_optimizer(OptimizerConfig(zero1=False))
    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size)

    state, p_sh, s_sh = create_train_state(model, optimizer, key, ids, zero1=False)
    step = build_train_step(model, optimizer, p_sh, s_sh)
    data = shard_batch({"input_ids": ids, "labels": jnp.roll(ids, -1, axis=1)})

    n_params = sum(p.size for p in jax.tree.leaves(state.params))

    # warmup (compile). NOTE: on the axon TPU relay block_until_ready does not
    # actually wait for device completion — a host readback (float()) is the
    # only reliable sync, so timing uses a two-point slope that cancels the
    # fixed readback RTT.
    for _ in range(2):
        state, metrics = step(state, data)
    _ = float(metrics["loss"])

    def timed(iters):
        nonlocal state
        t0 = time.perf_counter()
        m = None
        for _ in range(iters):
            state, m = step(state, data)
        _ = float(m["loss"])  # force full pipeline completion
        return time.perf_counter() - t0

    n1, n2 = (3, 13) if on_tpu else (1, 4)
    t1 = timed(n1)
    t2 = timed(n2)
    dt = (t2 - t1) / (n2 - n1)
    if dt <= 0:  # fall back if noise dominates
        dt = t2 / n2

    tokens = batch * seq
    tokens_per_sec = tokens / dt
    flops_per_step = 6.0 * n_params * tokens  # fwd+bwd transformer estimate
    mfu = (flops_per_step / dt) / peak_flops_per_chip(devs[0])
    target_mfu = 0.40
    _emit(
        {
            "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
            "value": round(tokens_per_sec, 2),
            "unit": "tokens/s",
            "vs_baseline": round(mfu / target_mfu, 4),
            "extras": {
                "mfu": round(mfu, 4),
                "n_params": int(n_params),
                "step_time_s": round(dt, 4),
                "layers": cfg.num_layers,
                "platform": devs[0].platform,
                "attention_impl": attention_impl,
            },
        }
    )


def _parse_result(stdout: str):
    """Last stdout line that parses as a JSON object with a 'metric' key."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None


def main() -> None:
    errors = []
    # If the driver kills the harness mid-retry (its outer budget may be
    # shorter than ours), still flush a parseable error JSON on the way out.
    import signal

    def _on_term(signum, frame):
        _emit(
            {
                "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
                "value": 0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": "; ".join(errors + [f"killed by signal {signum} mid-attempt"]),
            }
        )
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    for attempt, timeout_s in enumerate(ATTEMPT_TIMEOUTS, 1):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timed out after {timeout_s}s (backend hang)")
            continue
        result = _parse_result(proc.stdout)
        if result is None:
            tail = (proc.stderr or proc.stdout or "").strip()[-400:]
            errors.append(f"attempt {attempt}: rc={proc.returncode}, no JSON: {tail}")
            continue
        if "error" in result and result.get("retryable") and attempt < len(ATTEMPT_TIMEOUTS):
            errors.append(f"attempt {attempt}: {result['error']}")
            continue
        if "error" in result:
            errors.append(f"attempt {attempt}: {result['error']}")
            result["error"] = "; ".join(errors)
            result.pop("retryable", None)
        print(json.dumps(result), flush=True)
        return
    _emit(
        {
            "metric": "llama2_7b_width_train_tokens_per_sec_per_chip",
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "error": "; ".join(errors) or "no attempt produced output",
        }
    )


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        main()
